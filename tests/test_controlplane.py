"""Self-healing control plane (docs/controlplane.md).

Covers the reconcile loop (burn/backlog scale-up, idle scale-down with
the measured-capacity guard, cooldown + action rate limit), the
degradation ladder's hysteresis and its admission actuation at the
overload shedder, replica pools (local engines + exec contract), the
operator surfaces (overview block, pause/resume, /health visibility),
the autoscaler clock-discipline satellite — and the two CHAOS
scenarios the acceptance criteria pin: a seeded replica kill
mid-stream at 2-chunk pipeline depth (controller replaces it,
InvariantChecker proves zero-loss/zero-dup/monotone, recovery lands
inside the configured budget) and a flapping replica (breaker +
controller don't thrash: the scale-action rate limit holds).
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional

import pytest

from llmq_tpu import chaos
from llmq_tpu.api.overload import OverloadShedder
from llmq_tpu.api.server import ApiServer
from llmq_tpu.chaos import InvariantChecker
from llmq_tpu.cluster.router import ClusterRouter
from llmq_tpu.controlplane import (DegradationLadder, LocalEnginePool,
                                   ReplicaController, build_controller)
from llmq_tpu.controlplane.pool import ExecReplicaPool
from llmq_tpu.core.clock import FakeClock
from llmq_tpu.core.config import (BreakerConfig, ChaosConfig,
                                  ClusterConfig, ControlPlaneConfig,
                                  LoadBalancerConfig, OverloadConfig,
                                  ReplicaPoolConfig, SupervisorConfig,
                                  default_config, default_rungs)
from llmq_tpu.core.types import Message, Priority
from llmq_tpu.engine import ByteTokenizer, EchoExecutor, InferenceEngine
from llmq_tpu.loadbalancer.load_balancer import (Endpoint,
                                                 EndpointStatus,
                                                 LoadBalancer)
from llmq_tpu.queueing.dead_letter_queue import DeadLetterQueue
from llmq_tpu.queueing.queue_manager import QueueManager
from llmq_tpu.queueing.worker import Worker

pytestmark = [
    # The chaos kill scenario crashes engine threads on purpose.
    pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning"),
]


@pytest.fixture(autouse=True)
def _chaos_reset():
    yield
    chaos.configure(None)


class FakeBurn:
    """Injectable SLO-tracker stand-in: tests set the burn directly."""

    def __init__(self) -> None:
        self.fast = 0.0
        self.slow = 0.0

    def burn_rates(self) -> Dict:
        return {"ttft": {"5m": {"burn_rate": self.fast},
                         "1h": {"burn_rate": self.slow}}}


class FakeManager:
    def __init__(self, pending: int = 0) -> None:
        self.pending = pending

    def total_pending(self) -> int:
        return self.pending


def _echo_engine(name: str, *, pipelined: bool = False,
                 step_delay_s: float = 0.0) -> InferenceEngine:
    from llmq_tpu.core.config import AsyncPipelineConfig
    tok = ByteTokenizer()
    ex = EchoExecutor(batch_size=8, page_size=8, num_pages=512,
                      max_pages_per_seq=16, eos_id=tok.eos_id,
                      chunk_size=4, async_chunks=pipelined,
                      step_delay_s=step_delay_s)
    return InferenceEngine(
        ex, tok, name=name, enable_metrics=False, max_decode_steps=32,
        async_pipeline=(AsyncPipelineConfig(enabled=True, depth=2)
                        if pipelined else None))


def _router(**cluster_kw) -> ClusterRouter:
    lb = LoadBalancer(LoadBalancerConfig(strategy="round_robin",
                                         health_check_interval=0.0))
    cluster_kw.setdefault("failover_retries", 3)
    cluster_kw.setdefault(
        "breaker", BreakerConfig(failure_threshold=3,
                                 base_backoff=0.05, jitter=0.2))
    return ClusterRouter(lb, config=ClusterConfig(**cluster_kw),
                         enable_metrics=False)


def _controller(router, *, pool=None, cfg: Optional[ControlPlaneConfig] = None,
                burn: Optional[FakeBurn] = None,
                manager=None, shedder=None, clock=None,
                supervisor=None) -> ReplicaController:
    return ReplicaController(
        config=cfg or ControlPlaneConfig(enabled=True, interval=0.0),
        router=router, pool=pool, queue_manager=manager,
        shedder=shedder, slo_tracker=burn or FakeBurn(),
        supervisor=supervisor, clock=clock, enable_metrics=False)


def _pool(prefix: str = "pool", *, pipelined: bool = False,
          max_restarts: int = 0) -> LocalEnginePool:
    def factory(seq: int) -> InferenceEngine:
        return _echo_engine(f"{prefix}-{seq}", pipelined=pipelined)

    return LocalEnginePool(
        factory, supervise=True,
        supervisor_config=SupervisorConfig(check_interval=0.02,
                                           max_restarts=max_restarts))


def _stack(process_like, checker, name: str, *, backoff: float = 0.05):
    """QueueManager + Worker + DLQ wired into the invariant checker
    (the chaos-plane harness pattern from tests/test_chaos.py)."""
    cfg = default_config()
    cfg.queue.enable_metrics = False
    cfg.queue.worker.process_interval = 0.005
    cfg.queue.retry.initial_backoff = backoff
    cfg.queue.retry.max_backoff = backoff * 4
    mgr = QueueManager(name, config=cfg, enable_metrics=False)
    dlq = DeadLetterQueue(name=f"{name}-dlq")
    dlq.add_handler(lambda item: checker.dead_lettered(item.message.id))
    orig_complete = mgr.complete_message

    def complete(m, t=0.0, q=None):
        checker.completed(m.id)
        orig_complete(m, t, q)

    mgr.complete_message = complete
    worker = Worker("w0", mgr, process_like.process_fn,
                    dead_letter_queue=dlq)
    return mgr, worker, dlq


def _await(pred, timeout: float = 30.0, msg: str = "condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# -- reconcile-loop unit behavior ---------------------------------------------

class TestReconcile:
    def test_bootstrap_to_min_replicas(self):
        router = _router()
        ctl = _controller(router, pool=_pool("boot"),
                          cfg=ControlPlaneConfig(
                              enabled=True, interval=0.0,
                              min_replicas=2, max_replicas=4))
        try:
            out = ctl.run_once()
            assert out["target"] == 2
            assert len(router.lb.endpoints()) == 2
            assert [a for a, _ in out["actions"]].count("scale_up") == 2
        finally:
            ctl.stop()

    def test_burn_drives_scale_up_with_cooldown(self):
        clock = FakeClock()
        burn = FakeBurn()
        router = _router()
        cfg = ControlPlaneConfig(enabled=True, interval=0.0,
                                 min_replicas=1, max_replicas=4,
                                 cooldown=10.0)
        ctl = _controller(router, pool=_pool("burnup"), cfg=cfg,
                          burn=burn, clock=clock)
        try:
            ctl.run_once()                       # bootstrap → 1
            assert ctl.target == 1
            burn.fast = cfg.fast_burn_threshold + 1
            out = ctl.run_once()
            assert ("scale_up", "burn_fast") in out["actions"]
            assert ctl.target == 2
            # Cooldown: the very next hot tick must NOT scale again.
            out = ctl.run_once()
            assert ("skip", "cooldown") in out["actions"]
            assert ctl.target == 2
            clock.advance(11.0)
            out = ctl.run_once()
            assert ctl.target == 3
            # Slow-window burn is its own trigger.
            burn.fast = 0.0
            burn.slow = cfg.slow_burn_threshold + 1
            clock.advance(11.0)
            out = ctl.run_once()
            assert ("scale_up", "burn_slow") in out["actions"]
            assert ctl.target == 4
            # max_replicas is a hard ceiling.
            clock.advance(11.0)
            out = ctl.run_once()
            assert ctl.target == 4
        finally:
            ctl.stop()

    def test_backlog_drives_scale_up(self):
        clock = FakeClock()
        router = _router()
        mgr = FakeManager(pending=1000)
        cfg = ControlPlaneConfig(enabled=True, interval=0.0,
                                 backlog_per_replica=64,
                                 max_replicas=4, cooldown=0.0)
        ctl = _controller(router, pool=_pool("backlog"), cfg=cfg,
                          manager=mgr, clock=clock)
        try:
            ctl.run_once()
            out = ctl.run_once()
            assert ("scale_up", "backlog") in out["actions"]
        finally:
            ctl.stop()

    def test_idle_scale_down_drains_then_decommissions(self):
        clock = FakeClock()
        router = _router()
        pool = _pool("down")
        cfg = ControlPlaneConfig(enabled=True, interval=0.0,
                                 min_replicas=1, max_replicas=4,
                                 cooldown=5.0)
        ctl = _controller(router, pool=pool, cfg=cfg, clock=clock,
                          manager=FakeManager(0))
        try:
            ctl.run_once()
            ctl.target = 3
            ctl.run_once()                       # provisions to 3
            assert len(router.lb.endpoints()) == 3
            clock.advance(6.0)
            out = ctl.run_once()                 # idle → drain one
            assert ("scale_down", "idle") in out["actions"]
            assert ctl.target == 2
            draining = [e for e in router.lb.endpoints()
                        if e.status == EndpointStatus.DRAINING]
            assert len(draining) == 1
            out = ctl.run_once()                 # idle endpoint reaped
            eps = router.lb.endpoints()           # (cooldown holds the
            assert len(eps) == 2                  # next scale-down)
            assert all(e.status != EndpointStatus.DRAINING
                       for e in eps)
            assert pool.decommissioned == 1
            # Keep idling: converges to min_replicas and STOPS there.
            for _ in range(6):
                clock.advance(6.0)
                ctl.run_once()
            assert ctl.target == 1
            assert len(router.lb.endpoints()) == 1
        finally:
            ctl.stop()

    def test_capacity_guard_blocks_scale_down(self):
        """The measured tokens/s must keep headroom after a drain —
        otherwise the idle branch is vetoed (reason=capacity skip)."""
        clock = FakeClock()
        router = _router()
        cfg = ControlPlaneConfig(enabled=True, interval=0.0,
                                 min_replicas=1, cooldown=0.0,
                                 scale_down_headroom=1.5)
        ctl = _controller(router, pool=_pool("cap"), cfg=cfg,
                          clock=clock, manager=FakeManager(0))
        try:
            ctl.run_once()
            ctl.target = 2
            ctl.run_once()
            # Simulate a measured-load observation: peak 100 tok/s per
            # replica, current load 150 tok/s → one replica (100) can't
            # cover 150×1.5; the guard must veto.
            ctl._peak_replica_tok_s = 100.0
            obs = {"tokens_per_s": 150.0}
            assert not ctl._capacity_allows_scale_down(obs, healthy_n=2)
            assert ctl.action_counts.get("skip:capacity") == 1
            # Load falls → scale-down allowed again.
            assert ctl._capacity_allows_scale_down(
                {"tokens_per_s": 40.0}, healthy_n=2)
        finally:
            ctl.stop()

    def test_action_rate_limit_holds(self):
        """The thrash guard: no more than max_actions_per_minute
        scale/replace actions in any rolling 60s window."""
        clock = FakeClock()
        burn = FakeBurn()
        router = _router()
        cfg = ControlPlaneConfig(enabled=True, interval=0.0,
                                 min_replicas=1, max_replicas=8,
                                 cooldown=0.0, max_actions_per_minute=3)
        ctl = _controller(router, pool=_pool("thrash"), cfg=cfg,
                          burn=burn, clock=clock)
        try:
            ctl.run_once()                       # bootstrap (1 action)
            burn.fast = 100.0
            for _ in range(10):
                ctl.run_once()
                clock.advance(0.5)
            assert ctl.scale_action_total() <= 3
            assert ctl.action_counts.get("skip:rate_limited", 0) > 0
            # Window expires → actions resume.
            clock.advance(61.0)
            out = ctl.run_once()
            assert ("scale_up", "burn_fast") in out["actions"]
            # <= 0 disables the limit entirely (repo "0 = unlimited").
            ctl.config.max_actions_per_minute = 0
            before = ctl.scale_action_total()
            for _ in range(4):
                ctl.run_once()
            assert ctl.scale_action_total() >= before + 3
        finally:
            ctl.stop()

    def test_down_static_peer_does_not_pin_fleet_or_recovery(self):
        """An UNHEALTHY endpoint the controller does NOT own (a static
        peer) must block neither idle scale-down nor recovery
        completion — it is not the controller's to fix."""
        clock = FakeClock()
        router = _router()
        dead_peer = Endpoint(id="peer-down", url="http://10.0.0.9:1",
                             status=EndpointStatus.UNHEALTHY)
        router.lb.add_endpoint(dead_peer)
        cfg = ControlPlaneConfig(enabled=True, interval=0.0,
                                 min_replicas=1, max_replicas=4,
                                 cooldown=5.0)
        ctl = _controller(router, pool=_pool("peerdown"), cfg=cfg,
                          clock=clock, manager=FakeManager(0))
        try:
            ctl.run_once()
            ctl.target = 3
            ctl.run_once()
            clock.advance(6.0)
            out = ctl.run_once()         # idle despite the dead peer
            assert ("scale_down", "idle") in out["actions"]
            # Recovery must also close over the dead peer: simulate a
            # replacement having happened.
            ctl._recovering_since = clock.now() - 2.0
            clock.advance(6.0)
            ctl.run_once()
            assert ctl.last_recovery_s is not None
        finally:
            ctl.stop()

    def test_pause_still_reaps_inflight_drain(self):
        """Pause stops NEW decisions; a drain already in flight is
        still completed (a drained replica must not burn
        replica-seconds for the whole pause)."""
        clock = FakeClock()
        router = _router()
        pool = _pool("pausedrain")
        cfg = ControlPlaneConfig(enabled=True, interval=0.0,
                                 min_replicas=1, cooldown=5.0)
        ctl = _controller(router, pool=pool, cfg=cfg, clock=clock,
                          manager=FakeManager(0))
        try:
            ctl.run_once()
            ctl.target = 2
            ctl.run_once()
            clock.advance(6.0)
            out = ctl.run_once()         # starts the drain
            assert ("scale_down", "idle") in out["actions"]
            ctl.pause()
            out = ctl.run_once()         # paused tick reaps it
            assert out["paused"] is True
            assert len(router.lb.endpoints()) == 1
            assert pool.decommissioned == 1
        finally:
            ctl.stop()

    def test_paused_observes_but_never_acts(self):
        burn = FakeBurn()
        router = _router()
        ctl = _controller(router, pool=_pool("paused"), burn=burn,
                          cfg=ControlPlaneConfig(
                              enabled=True, interval=0.0,
                              min_replicas=2, cooldown=0.0))
        try:
            ctl.pause()
            burn.fast = 100.0
            out = ctl.run_once()
            assert out["paused"] is True
            assert out["actions"] == []
            assert len(router.lb.endpoints()) == 0   # nothing built
            snap = ctl.snapshot()
            assert snap["paused"] is True
            assert snap["inputs"]["fast_burn"] == 100.0  # still fresh
            burn.fast = 0.0
            ctl.resume()
            out = ctl.run_once()
            assert out["paused"] is False
            assert len(router.lb.endpoints()) == 2   # acts again
            assert ctl.action_counts.get("pause:operator") == 1
            assert ctl.action_counts.get("resume:operator") == 1
        finally:
            ctl.stop()


# -- degradation ladder -------------------------------------------------------

class TestLadder:
    def _shedder(self, registry=None):
        cfg = OverloadConfig(enabled=True, queue_depth_limit=100,
                             deadline_headroom=1.0)
        return OverloadShedder(cfg, None, tenant_registry=registry,
                               enable_metrics=False)

    def test_hysteresis_escalate_and_relax(self):
        ladder = DegradationLadder(default_rungs(),
                                   relax_after_ticks=3)
        assert ladder.tick(hot=True, calm=False) == "escalate"
        assert ladder.level == 1
        assert ladder.tick(hot=True, calm=False) == "escalate"
        assert ladder.tick(hot=True, calm=False) == "escalate"
        assert ladder.level == 3
        assert ladder.tick(hot=True, calm=False) is None  # top rung
        # Two calm ticks then a neutral tick: the streak resets.
        assert ladder.tick(hot=False, calm=True) is None
        assert ladder.tick(hot=False, calm=True) is None
        assert ladder.tick(hot=False, calm=False) is None
        assert ladder.level == 3
        # Three CONSECUTIVE calm ticks relax exactly one rung.
        for _ in range(2):
            assert ladder.tick(hot=False, calm=True) is None
        assert ladder.tick(hot=False, calm=True) == "relax"
        assert ladder.level == 2

    def test_rungs_tighten_admission_in_order(self):
        """Rung 2 sheds the batch tier with an explicit 429
        reason=degraded; rung 0 restores byte-identical admission."""
        from llmq_tpu.api.server import ApiError
        shedder = self._shedder()
        ladder = DegradationLadder(default_rungs(), shedder=shedder,
                                   relax_after_ticks=1)
        low = Message(id="m-low", content="x", user_id="u",
                      priority=Priority.LOW)
        rt = Message(id="m-rt", content="x", user_id="u",
                     priority=Priority.REALTIME)
        shedder.admit(low, None, 0.0)            # level 0: admitted
        ladder.tick(hot=True, calm=False)        # rung 1: tighten only
        shedder.admit(low, None, 0.0)            # still admitted
        ladder.tick(hot=True, calm=False)        # rung 2: shed batch
        with pytest.raises(ApiError) as ei:
            shedder.admit(low, None, 0.0)
        assert ei.value.status == 429
        assert "degraded" in ei.value.message
        shedder.admit(rt, None, 0.0)             # realtime survives
        assert shedder.get_stats()["shed"]["degraded"] == 1
        assert shedder.get_stats()["degradation"] == "shed_batch"
        ladder.tick(hot=False, calm=True)        # relax → rung 1
        shedder.admit(low, None, 0.0)            # batch admitted again
        ladder.tick(hot=False, calm=True)        # rung 0
        assert shedder._degradation is None      # noqa: SLF001
        assert shedder.get_stats()["degradation"] is None

    def test_backlog_and_headroom_factors_scale_thresholds(self):
        from llmq_tpu.api.server import ApiError
        shedder = self._shedder()
        mgr = FakeManager(pending=80)            # under the 100 limit
        msg = Message(id="m0", content="x", user_id="u")
        shedder.admit(msg, mgr, 0.0)             # admitted at level 0
        shedder.set_degradation({"name": "tighten",
                                 "backlog_factor": 0.7})
        with pytest.raises(ApiError) as ei:      # 80 >= 100×0.7
            shedder.admit(msg, mgr, 0.0)
        assert ei.value.status == 429
        assert "backlog" in ei.value.message

    def test_low_weight_tenants_shed_last_rung(self):
        from llmq_tpu import tenancy
        from llmq_tpu.api.server import ApiError
        from llmq_tpu.core.config import TenancyConfig
        reg = tenancy.configure_tenancy(TenancyConfig(
            enabled=True,
            tenants={"gold": {"weight": 4.0},
                     "bronze": {"weight": 0.5}}))
        try:
            shedder = self._shedder(registry=reg)
            shedder.set_degradation(default_rungs()[2])
            gold = Message(id="g0", content="x", user_id="u",
                           priority=Priority.REALTIME,
                           tenant_id="gold")
            bronze = Message(id="b0", content="x", user_id="u",
                             priority=Priority.REALTIME,
                             tenant_id="bronze")
            shedder.admit(gold, None, 0.0)       # weight 4 ≥ 1.0: kept
            with pytest.raises(ApiError) as ei:  # weight .5 < 1.0: shed
                shedder.admit(bronze, None, 0.0)
            assert ei.value.status == 429
            assert "weight" in ei.value.message
        finally:
            tenancy.reset_tenancy()

    def test_controller_ladder_integration(self):
        """Hot burn escalates before scaling alone can help; calm burn
        relaxes in reverse order — all through run_once."""
        clock = FakeClock()
        burn = FakeBurn()
        router = _router()
        shedder = self._shedder()
        cfg = ControlPlaneConfig(enabled=True, interval=0.0,
                                 min_replicas=1, max_replicas=1,
                                 cooldown=0.0, relax_after_ticks=2)
        ctl = _controller(router, pool=_pool("lad"), cfg=cfg,
                          burn=burn, clock=clock, shedder=shedder)
        try:
            ctl.run_once()
            burn.fast = 2.0                      # ≥ escalate_burn
            out = ctl.run_once()
            assert ("escalate", "burn_fast") in out["actions"]
            assert ctl.ladder.level == 1
            assert shedder._degradation is not None  # noqa: SLF001
            burn.fast = 0.0
            ctl.run_once()
            out = ctl.run_once()
            assert ("relax", "recovered") in out["actions"]
            assert ctl.ladder.level == 0
            assert shedder._degradation is None  # noqa: SLF001
        finally:
            ctl.stop()


# -- chaos scenarios (the acceptance criteria) --------------------------------

class TestChaosRecovery:
    @pytest.mark.chaos
    def test_kill_replica_mid_stream_controller_restores_slo(self):
        """THE acceptance scenario: a seeded EngineCrash kills replica
        pool-1 mid-stream with the async pipeline at 2 chunks in
        flight. Its supervisor gives up (fails out of rotation), the
        controller decommissions and replaces it, failover + retry own
        the in-between — and the InvariantChecker proves zero loss,
        zero duplicate completions, monotone streams, with recovery
        (kill→target-met, burn < 1.0 on the fast window) inside the
        configured budget."""
        chaos.configure(ChaosConfig(enabled=True, seed=11, faults=[
            {"point": "engine.step", "kind": "crash", "times": 1,
             "after": 8, "match": {"engine": "kill-1"}}]))
        checker = InvariantChecker()
        router = _router()
        pool = _pool("kill", pipelined=True, max_restarts=0)
        cfg = ControlPlaneConfig(enabled=True, interval=0.0,
                                 min_replicas=2, max_replicas=3,
                                 cooldown=0.0, recovery_budget_s=20.0)
        ctl = _controller(router, pool=pool, cfg=cfg)
        mgr, worker, dlq = _stack(router, checker, "killrec")
        t_kill: Dict[str, float] = {}
        try:
            ctl.run_once()                       # bootstrap 2 replicas
            assert len(router.lb.endpoints()) == 2
            # A LIVE token stream on the doomed replica: the crash
            # lands mid-stream (2 chunks speculated in flight), and
            # the monotone invariant must hold — streamed tokens are a
            # prefix of the recorded result, never replayed/extended.
            from llmq_tpu.engine.engine import GenRequest
            doomed = router.lb.get_endpoint_by_id("kill-1") \
                .metadata["engine"]
            sh = doomed.submit(
                GenRequest(id="stream0", prompt="stream through the "
                                                "kill " * 4,
                           max_new_tokens=32),
                on_token=checker.on_token("stream0"))
            checker.submitted("stream0")
            worker.start()
            for i in range(14):
                m = Message(id=f"k{i}", content=f"kill payload {i} " * 3,
                            user_id="u", timeout=25.0)
                checker.submitted(m.id)
                mgr.push_message(m)
            # Tick until the crash fires and the replica is replaced.
            deadline = time.time() + 20.0
            replaced = False
            while time.time() < deadline:
                ctl.run_once()
                if not replaced and ctl.action_counts.get(
                        "replace:replica_dead"):
                    replaced = True
                    t_kill["replaced_at"] = time.time()
                s = checker.summary()
                if (replaced
                        and sum(s["terminal"].values()) >= 14
                        and ctl.last_recovery_s is not None):
                    break
                time.sleep(0.05)
            # The mid-stream request died with the replica: its handle
            # was failed by the supervisor/decommission recovery, its
            # streamed tokens a PREFIX of the recorded result.
            assert sh.wait(5.0)
            assert sh.result.finish_reason == "error"
            assert len(checker._streams.get("stream0", [])) >= 2, \
                "crash was not mid-stream"
            checker.failed("stream0")
            checker.completed("stream0", tokens=sh.result.tokens)
            # (the completed record above only carries result tokens
            # for the monotone check — same terminal as the failure)
            checker._terminal["stream0"].remove("completed")
            s = checker.summary()
            assert sum(s["terminal"].values()) >= 15, s
        finally:
            worker.stop()
            mgr.stop()
            ctl.stop()
        checker.check()                  # zero loss/dup + monotone
        total = (s["terminal"].get("completed", 0)
                 + s["terminal"].get("dead_lettered", 0))
        assert total == 14, s            # every queued request landed
        assert s["terminal"].get("failed", 0) == 1   # the dead stream
        assert dlq.size() == 0                   # nothing even parked
        # The chaos plane really killed the engine…
        inj = chaos.get_injector()
        assert inj.get_stats()["injected"].get("engine.step:crash") == 1
        # …and the controller really replaced it.
        assert ctl.action_counts.get("replace:replica_dead", 0) >= 1
        # Recovery (replacement → back at target with burn<1) landed
        # inside the budget.
        assert ctl.last_recovery_s is not None
        assert ctl.last_recovery_s <= cfg.recovery_budget_s, \
            ctl.last_recovery_s
        # The cluster is whole again: 2 healthy replicas, pool-3 is
        # the replacement.
        eps = router.lb.endpoints()
        assert len(eps) == 2
        assert all(e.status in (EndpointStatus.HEALTHY,
                                EndpointStatus.DEGRADED) for e in eps)
        assert pool.get_stats()["provisioned"] == 3

    @pytest.mark.chaos
    def test_flapping_replica_breaker_and_controller_dont_thrash(self):
        """A flapping HTTP replica (seeded p=0.4 transport faults):
        breakers absorb the flaps, dispatch keeps succeeding via
        failover, and the controller neither replaces the flapping
        replica (its /health stays green) nor thrashes scale actions —
        the rate limit holds."""
        chaos.configure(ChaosConfig(enabled=True, seed=21, faults=[
            {"point": "transport.request", "kind": "error",
             "probability": 0.4}]))
        checker = InvariantChecker()
        engines, servers, urls = [], [], []
        for i in range(2):
            eng = _echo_engine(f"flapctl{i}")
            eng.start()
            api = ApiServer(default_config(), engine=eng)
            port = api.start(host="127.0.0.1", port=0)
            engines.append(eng)
            servers.append(api)
            urls.append(f"http://127.0.0.1:{port}")
        router = _router()
        for url in urls:
            router.register_remote(url,
                                   endpoint_id=url.split("//")[1])
        cfg = ControlPlaneConfig(enabled=True, interval=0.0,
                                 min_replicas=2, max_replicas=4,
                                 cooldown=0.0, max_actions_per_minute=2,
                                 backlog_per_replica=4)
        ctl = _controller(router, pool=_pool("flapspill"), cfg=cfg,
                          manager=None)
        mgr, worker, dlq = _stack(router, checker, "flapctl")
        ctl.queue_manager = mgr
        try:
            ctl.run_once()
            worker.start()
            for i in range(16):
                m = Message(id=f"fl{i}", content=f"flap {i}",
                            user_id="u", timeout=15.0)
                checker.submitted(m.id)
                mgr.push_message(m)
            deadline = time.time() + 40.0
            while time.time() < deadline:
                ctl.run_once()
                s = checker.summary()
                if sum(s["terminal"].values()) >= 16:
                    break
                time.sleep(0.05)
            s = checker.summary()
        finally:
            worker.stop()
            mgr.stop()
            for api in servers:
                api.stop()
            for eng in engines:
                eng.stop()
            ctl.stop()
        checker.check()
        total = (s["terminal"].get("completed", 0)
                 + s["terminal"].get("dead_lettered", 0))
        assert total == 16, s
        # Faults really flowed…
        inj = chaos.get_injector()
        assert inj.get_stats()["injected"].get(
            "transport.request:error", 0) > 0
        # …but the flapping replicas were never "replaced" (their
        # health stayed green — the breaker owns transient faults)…
        assert ctl.action_counts.get("replace:replica_dead", 0) == 0
        assert ctl.action_counts.get("replace:breaker_open", 0) == 0
        # …and total scale actions stayed inside the hard rate limit.
        assert ctl.scale_action_total() <= cfg.max_actions_per_minute


# -- operator surfaces --------------------------------------------------------

class TestApiSurfaces:
    def _server(self):
        router = _router()
        eng = _echo_engine("apisrv")
        router.register_engine(eng)
        ctl = _controller(router, pool=_pool("api"),
                          cfg=ControlPlaneConfig(enabled=True,
                                                 interval=0.0))
        srv = ApiServer(default_config(), engine=eng,
                        cluster_router=router, controller=ctl)
        return srv, ctl, eng

    def test_overview_gains_controller_block(self):
        srv, ctl, eng = self._server()
        try:
            ctl.run_once()
            status, payload, _ = srv.dispatch(
                "GET", "/api/v1/cluster/overview", b"")
            assert status == 200
            blk = payload["controller"]
            assert blk["enabled"] is True
            assert blk["paused"] is False
            assert blk["target_replicas"] >= 1
            assert "rung" in blk and "inputs" in blk
            assert "fast_burn" in blk["inputs"]
            assert "last_seconds" in blk["recovery"]
        finally:
            ctl.stop()
            eng.stop()

    def test_admin_pause_resume_and_health_visibility(self):
        srv, ctl, eng = self._server()
        try:
            status, payload, _ = srv.dispatch("GET", "/health", b"")
            assert payload["controller"] == "running"
            status, payload, _ = srv.dispatch(
                "POST", "/api/v1/admin/controller",
                json.dumps({"action": "pause"}).encode())
            assert status == 200 and payload["status"] == "paused"
            assert ctl.paused
            _, payload, _ = srv.dispatch("GET", "/health", b"")
            assert payload["controller"] == "paused"
            _, payload, _ = srv.dispatch(
                "GET", "/api/v1/admin/controller", b"")
            assert payload["paused"] is True
            status, payload, _ = srv.dispatch(
                "POST", "/api/v1/admin/controller",
                json.dumps({"action": "resume"}).encode())
            assert payload["status"] == "running"
            status, _, _ = srv.dispatch(
                "POST", "/api/v1/admin/controller",
                json.dumps({"action": "explode"}).encode())
            assert status == 400
        finally:
            ctl.stop()
            eng.stop()

    def test_disabled_is_distinct_from_paused(self):
        """No controller (controlplane.enabled=false): the admin route
        503s and /health carries NO controller field at all."""
        srv = ApiServer(default_config())
        status, _, _ = srv.dispatch(
            "POST", "/api/v1/admin/controller",
            json.dumps({"action": "pause"}).encode())
        assert status == 503
        _, payload, _ = srv.dispatch("GET", "/health", b"")
        assert "controller" not in payload


# -- wiring + off-switch ------------------------------------------------------

class TestWiring:
    def test_off_switch_builds_nothing(self):
        cfg = default_config()
        assert cfg.controlplane.enabled is False
        assert build_controller(cfg, router=object()) is None

    def test_app_wires_controller_over_local_engine(self):
        from llmq_tpu.__main__ import App
        cfg = default_config()
        cfg.executor.backend = "echo"
        cfg.queue.enable_metrics = False
        cfg.loadbalancer.health_check_interval = 0.0
        cfg.controlplane.enabled = True
        cfg.controlplane.interval = 0.0
        app = App(cfg, with_api=True, with_workers=True,
                  with_engine=True)
        try:
            # The controller forced a cluster router over the local
            # engine so provisioned replicas would receive traffic.
            assert app.cluster_router is not None
            assert app.controller is not None
            assert app.api.controller is app.controller
            assert app.controller.ladder is not None
            # The ladder actuates through the API server's shedder.
            assert app.controller.ladder.shedder is app.api.shedder
        finally:
            app.stop()

    def test_controller_supersedes_legacy_autoscaler(self):
        """Two reconcilers must never share one LoadBalancer: with the
        control plane on, serve's legacy threshold autoscaler is not
        built (it would strip endpoints the controller re-provisions);
        with it off, the autoscaler still runs."""
        from llmq_tpu.__main__ import App
        cfg = default_config()
        cfg.executor.backend = "echo"
        cfg.queue.enable_metrics = False
        cfg.loadbalancer.health_check_interval = 0.0
        cfg.controlplane.enabled = True
        cfg.controlplane.interval = 0.0
        app = App(cfg, with_api=True, with_workers=True,
                  with_engine=True, with_scheduler=True)
        try:
            assert app.controller is not None
            assert app.autoscaler is None
        finally:
            app.stop()
        cfg2 = default_config()
        cfg2.executor.backend = "echo"
        cfg2.queue.enable_metrics = False
        app2 = App(cfg2, with_api=True, with_workers=True,
                   with_engine=True, with_scheduler=True)
        try:
            assert app2.controller is None
            assert app2.autoscaler is not None
        finally:
            app2.stop()

    def test_load_exports_config_path_for_subprocess_replicas(self,
                                                              tmp_path,
                                                              monkeypatch):
        """--config must reach subprocess pool replicas: _load exports
        the resolved path as LLMQ_CONFIG so spawned children serve the
        SAME configuration instead of silently falling back to
        defaults."""
        import argparse
        import os

        from llmq_tpu.__main__ import _load
        cfg_file = tmp_path / "replica.yaml"
        cfg_file.write_text("server: {port: 9321}\n")
        monkeypatch.delenv("LLMQ_CONFIG", raising=False)
        args = argparse.Namespace(config=str(cfg_file), host=None,
                                  port=None, backend=None,
                                  log_format=None, peers=None)
        cfg = _load(args)
        assert cfg.server.port == 9321
        assert os.environ["LLMQ_CONFIG"] == str(cfg_file.resolve())

    def test_app_default_config_has_no_controller(self):
        from llmq_tpu.__main__ import App
        cfg = default_config()
        cfg.executor.backend = "echo"
        cfg.queue.enable_metrics = False
        app = App(cfg, with_api=True, with_workers=True,
                  with_engine=True)
        try:
            assert app.controller is None
            assert app.api.controller is None
        finally:
            app.stop()


# -- pools --------------------------------------------------------------------

class TestPools:
    def test_local_pool_lifecycle(self):
        pool = _pool("lifec")
        ep = pool.provision(1)
        assert ep is not None and ep.metadata["pool"] is True
        eng = ep.metadata["engine"]
        assert eng.running
        pool.decommission(ep)
        assert not eng.running
        stats = pool.get_stats()
        assert stats["provisioned"] == 1
        assert stats["decommissioned"] == 1

    def test_local_pool_decommission_recovers_crashed_engine(self):
        """Decommissioning a DEAD replica fails its in-flight handles
        over to the retry path (zero-loss depends on this)."""
        from llmq_tpu.engine.engine import GenRequest
        chaos.configure(ChaosConfig(enabled=True, seed=3, faults=[
            {"point": "engine.step", "kind": "crash", "times": 1,
             "match": {"engine": "dead-1"}}]))
        pool = _pool("dead", max_restarts=0)
        ep = pool.provision(1)
        eng = ep.metadata["engine"]
        h = eng.submit(GenRequest(id="d0", prompt="doomed",
                                  max_new_tokens=16))
        _await(lambda: not eng.running, 5.0, "engine crash")
        pool.decommission(ep)
        assert h.wait(2.0)
        assert h.result.finish_reason == "error"

    def test_exec_pool_contract(self, tmp_path):
        """provision_cmd → URL (stdout or template) → readiness gate
        on /health → ready Endpoint; decommission_cmd env contract;
        rollback on a replica that never becomes ready."""
        import threading
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        class _Health(BaseHTTPRequestHandler):
            def do_GET(self):
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"ok")

            def log_message(self, fmt, *args):
                pass

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Health)
        port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        try:
            marker = tmp_path / "decommissioned"
            cfg = ReplicaPoolConfig(
                kind="exec",
                provision_cmd=(f"echo ignored; "
                               f"echo http://127.0.0.1:{port}"),
                decommission_cmd=f"echo $LLMQ_REPLICA_ID >> {marker}",
                ready_timeout=5.0)
            pool = ExecReplicaPool(cfg)
            ep = pool.provision(7)
            assert ep is not None
            assert ep.url == f"http://127.0.0.1:{port}"
            assert ep.id == f"127.0.0.1:{port}"
            assert ep.metadata["pool"] is True
            pool.decommission(ep)
            assert marker.read_text().strip() == f"127.0.0.1:{port}"
            # url_template wins over stdout.
            cfg2 = ReplicaPoolConfig(
                kind="exec", provision_cmd="echo whatever",
                url_template=f"http://127.0.0.1:{port}",
                ready_timeout=5.0)
            ep2 = ExecReplicaPool(cfg2).provision(3)
            assert ep2 is not None
            assert ep2.url == f"http://127.0.0.1:{port}"
        finally:
            httpd.shutdown()
        # A failing provision_cmd yields None, not a crash.
        cfg3 = ReplicaPoolConfig(kind="exec", provision_cmd="exit 3")
        assert ExecReplicaPool(cfg3).provision(1) is None
        # A replica that never answers /health is rolled back: None,
        # and decommission_cmd runs so the orchestrator isn't left
        # scaled up.
        rollback = tmp_path / "rollback"
        cfg4 = ReplicaPoolConfig(
            kind="exec", provision_cmd="echo http://127.0.0.1:9",
            decommission_cmd=f"echo $LLMQ_REPLICA_SEQ >> {rollback}",
            ready_timeout=0.3)
        assert ExecReplicaPool(cfg4).provision(5) is None
        assert rollback.read_text().strip() == "5"

    def test_subprocess_pool_serves_real_replica(self):
        """One real ``python -m llmq_tpu serve`` echo replica: the
        pool provisions it ready, the router dispatches to it over
        HTTP, and decommission SIGTERMs it down."""
        import socket

        from llmq_tpu.controlplane.pool import SubprocessReplicaPool
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            base = s.getsockname()[1]
        pool = SubprocessReplicaPool(ReplicaPoolConfig(
            kind="subprocess", base_port=base,
            args=["--backend", "echo"], ready_timeout=45.0))
        router = _router()
        ep = pool.provision(0)
        try:
            assert ep is not None, "replica never became ready"
            router.lb.add_endpoint(ep)
            msg = Message(id="sub0", content="subprocess replica",
                          user_id="u", timeout=30.0)
            router.process_fn(None, msg)
            assert msg.response
        finally:
            pool.stop()
        assert pool.get_stats()["live"] == 0


# -- autoscaler clock-discipline satellite ------------------------------------

class TestAutoscalerClock:
    def test_adaptive_strategy_follows_injected_clock(self):
        """The time-of-day heuristic must read the INJECTED clock, so
        FakeClock drives scaling decisions deterministically (no
        wall-clock leakage)."""
        import calendar

        from llmq_tpu.core.config import SchedulerConfig
        from llmq_tpu.queueing.queue_manager import QueueManager
        from llmq_tpu.scheduling.autoscaler import Autoscaler

        # Wed 2026-07-29 11:00 local → business hours; 23:00 → off.
        biz = calendar.timegm((2026, 7, 29, 11, 0, 0))
        off = calendar.timegm((2026, 7, 29, 23, 0, 0))
        # timegm is UTC; shift so LOCAL time is the intended hour.
        shift = (calendar.timegm(time.localtime(biz))
                 - int(biz))
        clock = FakeClock(start=float(biz - shift))
        mgr = QueueManager("asclk", enable_metrics=False)
        lb = LoadBalancer(LoadBalancerConfig(
            health_check_interval=0.0))
        made = []

        def provision(seq):
            ep = Endpoint(id=f"as{seq}", url=f"local://as{seq}")
            made.append(ep)
            return ep

        a = Autoscaler(mgr, lb,
                       SchedulerConfig(strategy="adaptive",
                                       min_endpoints=1,
                                       max_endpoints=4, cooldown=0.0),
                       provision_fn=provision,
                       decommission_fn=lambda ep: None,
                       clock=clock)
        lb.add_endpoint(Endpoint(id="seed", url="local://seed"))
        out = a.run_once()
        # Business hours: scales toward max-1 = 3.
        assert out["action"] == "up"
        assert len(lb.endpoints()) == 3
        # Advance the SAME clock to 23:00 local → off-hours target 1.
        clock.advance(float(off - biz))
        out = a.run_once()
        assert out["action"] == "down"
        assert len(lb.endpoints()) == 1
        mgr.stop()
