"""Conversation service tests.

The reference has ZERO tests for any of its three conversation managers
(SURVEY.md §4); this covers the unified manager + both usable stores."""

import pytest

from llmq_tpu.core.config import ConversationConfig
from llmq_tpu.core.errors import ConversationNotFoundError
from llmq_tpu.core.types import Conversation, ConversationState, Message
from llmq_tpu.conversation import InMemoryStore, SqliteStore, StateManager


@pytest.fixture(params=["memory", "sqlite"])
def store(request, tmp_path):
    if request.param == "memory":
        yield InMemoryStore()
    else:
        s = SqliteStore(str(tmp_path / "conv.db"))
        yield s
        s.close()


@pytest.fixture
def sm(fake_clock, store) -> StateManager:
    cfg = ConversationConfig(max_context_length=100, max_idle_time=60.0,
                             ttl=3600.0, max_conversations_per_user=3,
                             max_conversations=10)
    return StateManager(cfg, store=store, clock=fake_clock)


class TestLifecycle:
    def test_get_or_create(self, sm):
        c = sm.get_or_create("c1", "u1")
        assert c.id == "c1" and c.user_id == "u1"
        assert sm.get_or_create("c1").id == "c1"
        assert sm.count() == 1

    def test_get_missing_raises(self, sm):
        with pytest.raises(ConversationNotFoundError):
            sm.get("nope")

    def test_create_and_delete(self, sm):
        c = sm.create("u1")
        assert sm.get(c.id)
        assert sm.delete(c.id)
        with pytest.raises(ConversationNotFoundError):
            sm.get(c.id)

    def test_update_state(self, sm):
        c = sm.create("u1")
        sm.update_state(c.id, ConversationState.PAUSED)
        assert sm.get(c.id).state == ConversationState.PAUSED


class TestMessagesAndContext:
    def test_add_message(self, sm):
        c = sm.add_message("c1", Message(content="hello", user_id="u1"))
        assert len(c.messages) == 1
        assert c.messages[0].conversation_id == "c1"

    def test_window_trims_by_chars(self, sm):
        # max_context_length=100 in the fixture.
        for i in range(10):
            sm.add_message("c1", Message(content="x" * 30, user_id="u1"))
        c = sm.get("c1")
        assert len(c.messages) == 3  # 90 chars fits, 120 does not
        total = sum(len(m.content) for m in c.messages)
        assert total <= 100

    def test_record_response_builds_context(self, sm):
        m = Message(content="q", user_id="u1")
        m.response = "a" * 80
        sm.add_message("c1", m)
        sm.record_response("c1", m)
        c = sm.get("c1")
        assert c.context == "a" * 80
        m2 = Message(content="q2", user_id="u1")
        m2.response = "b" * 80
        sm.record_response("c1", m2)
        # context capped at max_context_length.
        assert len(sm.get("c1").context) == 100
        assert sm.get("c1").context.endswith("b" * 80)


class TestPersistence:
    def test_reload_from_store_after_restart(self, fake_clock, store):
        cfg = ConversationConfig()
        sm1 = StateManager(cfg, store=store, clock=fake_clock)
        sm1.add_message("c1", Message(content="persisted", user_id="u1"))
        # "Restart": new manager, same store (state_manager.go:86-95).
        sm2 = StateManager(cfg, store=store, clock=fake_clock)
        c = sm2.get("c1")
        assert c.messages[0].content == "persisted"

    def test_user_conversations_include_archived(self, fake_clock, store):
        cfg = ConversationConfig(max_conversations_per_user=2)
        sm = StateManager(cfg, store=store, clock=fake_clock)
        ids = []
        for i in range(3):
            c = sm.create("u1")
            ids.append(c.id)
            fake_clock.advance(1.0)
        # Oldest archived out of memory but still listed via the store.
        assert sm.count() == 2
        got = {c.id for c in sm.user_conversations("u1")}
        assert got == set(ids)


class TestCleanup:
    def test_idle_eviction(self, sm, fake_clock):
        sm.create("u1")
        fake_clock.advance(61.0)  # max_idle_time=60
        assert sm.run_cleanup_once() == 1
        assert sm.count() == 0

    def test_active_not_evicted(self, sm, fake_clock):
        sm.create("u1")
        fake_clock.advance(30.0)
        assert sm.run_cleanup_once() == 0

    def test_ttl_eviction(self, fake_clock, store):
        cfg = ConversationConfig(ttl=100.0, max_idle_time=0)
        sm = StateManager(cfg, store=store, clock=fake_clock)
        c = sm.create("u1")
        fake_clock.advance(50.0)
        c.last_active_at = fake_clock.now()
        assert sm.run_cleanup_once() == 0
        fake_clock.advance(51.0)
        assert sm.run_cleanup_once() == 1

    def test_completed_linger(self, fake_clock, store):
        cfg = ConversationConfig(ttl=0, max_idle_time=0)
        sm = StateManager(cfg, store=store, clock=fake_clock)
        c = sm.create("u1")
        sm.update_state(c.id, ConversationState.COMPLETED)
        fake_clock.advance(23 * 3600.0)
        assert sm.run_cleanup_once() == 0
        fake_clock.advance(2 * 3600.0)
        assert sm.run_cleanup_once() == 1


class TestKVPinningHooks:
    def test_touch_and_evict_hooks(self, sm, fake_clock):
        touched, evicted = [], []
        sm.on_touch(lambda c: touched.append(c.id))
        sm.on_evict(lambda c: evicted.append(c.id))
        sm.get_or_create("c1", "u1")
        assert touched == ["c1"]
        fake_clock.advance(61.0)
        sm.run_cleanup_once()
        assert evicted == ["c1"]

    def test_hook_failure_does_not_break(self, sm):
        sm.on_touch(lambda c: (_ for _ in ()).throw(RuntimeError("hook")))
        c = sm.get_or_create("c1", "u1")  # no raise
        assert c.id == "c1"


class TestCaps:
    def test_global_cap(self, fake_clock, store):
        cfg = ConversationConfig(max_conversations=2,
                                 max_conversations_per_user=100)
        sm = StateManager(cfg, store=store, clock=fake_clock)
        for i in range(3):
            sm.create(f"u{i}")
            fake_clock.advance(1.0)
        assert sm.count() == 2


class _FakePipeline:
    def __init__(self, r):
        self._r = r
        self._ops = []

    def __getattr__(self, name):
        def op(*a, **kw):
            self._ops.append((name, a, kw))
            return self
        return op

    def execute(self):
        for name, a, kw in self._ops:
            getattr(self._r, name)(*a, **kw)
        self._ops = []


class _FakeRedis:
    """Minimal redis-protocol double covering exactly what RedisStore
    uses (get/set/sadd/smembers/srem/delete/expire/pipeline/close);
    values round-trip as bytes like the real client."""

    def __init__(self):
        self.kv = {}
        self.sets = {}
        self.ttls = {}

    def set(self, k, v, ex=None):
        self.kv[k] = v.encode() if isinstance(v, str) else v
        if ex is not None:
            self.ttls[k] = ex

    def get(self, k):
        return self.kv.get(k)

    def delete(self, k):
        self.kv.pop(k, None)
        self.sets.pop(k, None)

    def sadd(self, k, *members):
        self.sets.setdefault(k, set()).update(
            m.encode() if isinstance(m, str) else m for m in members)

    def smembers(self, k):
        return set(self.sets.get(k, set()))

    def srem(self, k, *members):
        s = self.sets.get(k, set())
        for m in members:
            s.discard(m.encode() if isinstance(m, str) else m)

    def expire(self, k, ttl):
        self.ttls[k] = ttl

    def pipeline(self):
        return _FakePipeline(self)

    def close(self):
        pass


def _real_redis():
    """A live Redis (client lib + reachable server) or None. The CI
    workflow runs a redis:7 service so TestRedisStoreReal executes
    there; locally it skips when no server is up."""
    try:
        import redis
    except ImportError:
        return None
    try:
        client = redis.Redis.from_url("redis://localhost:6379/0",
                                      socket_connect_timeout=0.3,
                                      socket_timeout=0.5)
        client.ping()
        return client
    except Exception:  # noqa: BLE001 — any failure means "unavailable"
        return None


@pytest.mark.skipif(_real_redis() is None,
                    reason="no real redis server/client available")
class TestRedisStoreReal:
    """The SAME contract as TestRedisStore, against a REAL server
    (VERDICT r3 #10): exercises actual RESP encoding, server-side TTLs
    and set semantics the in-memory double can only approximate."""

    @pytest.fixture
    def rstore(self):
        from llmq_tpu.conversation.persistence import RedisStore
        client = _real_redis()
        store = RedisStore("redis://localhost:6379/0",
                           prefix="llmq-test:", ttl=60.0, client=client)
        yield store
        for k in client.scan_iter("llmq-test:*"):
            client.delete(k)
        store.close()

    def test_roundtrip_and_user_index(self, rstore):
        c = Conversation(id="cr1", user_id="u1")
        c.add_message("hello", "hi there")
        rstore.save(c)
        back = rstore.load("cr1")
        assert back is not None
        assert back.id == "cr1" and back.user_id == "u1"
        assert back.messages[0].content == "hello"
        assert rstore.list_user("u1") == ["cr1"]

    def test_delete_removes_blob_and_membership(self, rstore):
        for cid in ("ca", "cb"):
            rstore.save(Conversation(id=cid, user_id="u2"))
        rstore.delete("ca")
        assert rstore.load("ca") is None
        assert rstore.list_user("u2") == ["cb"]
        assert rstore.load("cb") is not None

    def test_server_side_ttl_set(self, rstore):
        rstore.save(Conversation(id="ct", user_id="u3"))
        client = _real_redis()
        ttl = client.ttl("llmq-test:ct")
        assert 0 < ttl <= 60
        uttl = client.ttl("llmq-test:user:u3")
        assert 0 < uttl <= 60


class TestRedisStore:
    """RedisStore against an injected in-memory client: exercises the
    reference's key scheme (persistence.go:46-82) — {prefix}{conv_id}
    JSON blob + {prefix}user:{uid} membership set, TTL on both."""

    @pytest.fixture
    def rstore(self):
        from llmq_tpu.conversation.persistence import RedisStore
        fake = _FakeRedis()
        return RedisStore(prefix="llmq:", ttl=3600, client=fake), fake

    def test_save_load_roundtrip(self, rstore):
        store, fake = rstore
        conv = Conversation(id="c1", user_id="u1")
        conv.messages.append(Message(id="m1", content="hi", user_id="u1"))
        store.save(conv)
        assert "llmq:c1" in fake.kv                      # blob key
        assert b"c1" in fake.sets["llmq:user:u1"]        # membership set
        assert fake.ttls["llmq:c1"] == 3600              # TTL applied
        got = store.load("c1")
        assert got is not None and got.id == "c1"
        assert got.messages[0].content == "hi"

    def test_list_user_and_delete(self, rstore):
        store, fake = rstore
        for i in range(3):
            store.save(Conversation(id=f"c{i}", user_id="u1"))
        assert store.list_user("u1") == ["c0", "c1", "c2"]
        store.delete("c1")
        assert store.load("c1") is None
        assert store.list_user("u1") == ["c0", "c2"]

    def test_state_manager_over_redis(self, fake_clock):
        """The unified conversation service runs end-to-end over the
        redis backend: restart reloads from the store."""
        from llmq_tpu.conversation.persistence import RedisStore
        fake = _FakeRedis()
        cfg = ConversationConfig(persist=True)
        sm = StateManager(cfg, store=RedisStore(client=fake),
                          clock=fake_clock)
        conv = sm.create("u9")
        sm.add_message(conv.id, Message(id="m", content="x", user_id="u9"))
        sm2 = StateManager(cfg, store=RedisStore(client=fake),
                           clock=fake_clock)
        got = sm2.get(conv.id)
        assert got is not None and got.messages[0].content == "x"
