"""Foundation tests: data model, config tree, clocks.

Covers the surface of reference pkg/models/message.go and
pkg/config/config.go (the reference has no tests for either)."""

import os

import pytest

from llmq_tpu.core.clock import FakeClock
from llmq_tpu.core.config import (
    Config,
    default_config,
    load_config,
)
from llmq_tpu.core.types import (
    Conversation,
    ConversationState,
    Message,
    MessageStatus,
    Priority,
    PRIORITY_TIERS,
)


class TestPriority:
    def test_ordering(self):
        # Lower value = more urgent (reference message.go:15-22).
        assert Priority.REALTIME < Priority.HIGH < Priority.NORMAL < Priority.LOW

    def test_tier_names(self):
        assert PRIORITY_TIERS == ("realtime", "high", "normal", "low")
        assert Priority.REALTIME.tier_name == "realtime"

    def test_parse(self):
        assert Priority.parse("2") == Priority.HIGH
        assert Priority.parse("high") == Priority.HIGH
        assert Priority.parse(3) == Priority.NORMAL
        assert Priority.parse(Priority.LOW) == Priority.LOW
        with pytest.raises(ValueError):
            Priority.parse("urgent-ish")


class TestMessage:
    def test_defaults(self):
        # max_retries=3, timeout=30s (reference message.go:76-91).
        m = Message(content="hi")
        assert m.max_retries == 3
        assert m.timeout == 30.0
        assert m.status == MessageStatus.PENDING
        assert m.priority == Priority.NORMAL
        assert m.id  # uuid assigned

    def test_roundtrip(self):
        m = Message(content="hello", priority=Priority.HIGH,
                    metadata={"user_priority": 1})
        m2 = Message.from_dict(m.to_dict())
        assert m2.id == m.id
        assert m2.priority == Priority.HIGH
        assert m2.metadata == {"user_priority": 1}

    def test_can_retry(self):
        m = Message(max_retries=2)
        assert m.can_retry()
        m.retry_count = 2
        assert not m.can_retry()


class TestConversation:
    def test_roundtrip(self):
        c = Conversation(user_id="u1")
        c.messages.append(Message(content="hi", conversation_id=c.id))
        d = c.to_dict()
        assert d["message_count"] == 1
        c2 = Conversation.from_dict(d)
        assert c2.id == c.id and len(c2.messages) == 1
        assert c2.state == ConversationState.ACTIVE


class TestConfig:
    def test_defaults_match_reference(self):
        # The canonical 4 tiers (reference config.go:151-156).
        cfg = default_config()
        tiers = {lvl.priority: lvl for lvl in cfg.queue.levels}
        assert tiers[1].max_wait_time == 1.0 and tiers[1].max_concurrent == 100
        assert tiers[2].max_wait_time == 5.0 and tiers[2].max_concurrent == 200
        assert tiers[3].max_wait_time == 30.0 and tiers[3].max_concurrent == 500
        assert tiers[4].max_wait_time == 300.0 and tiers[4].max_concurrent == 1000
        # Worker defaults (config.go:169-173).
        assert cfg.queue.worker.max_batch_size == 10
        assert cfg.queue.worker.process_interval == 0.1
        assert cfg.queue.worker.max_concurrent == 50
        # Retry defaults (config.go:174-179).
        assert cfg.queue.retry.initial_backoff == 1.0
        assert cfg.queue.retry.max_backoff == 60.0
        assert cfg.queue.retry.backoff_multiplier == 2.0
        assert cfg.queue.retry.max_retries == 3

    def test_yaml_load_and_env_override(self, tmp_path, monkeypatch):
        p = tmp_path / "c.yaml"
        p.write_text("server: {port: 9999}\nqueue: {max_queue_size: 42}\n")
        monkeypatch.setenv("LLMQ_SERVER_HOST", "1.2.3.4")
        monkeypatch.setenv("LLMQ_QUEUE_WORKER_MAX_CONCURRENT", "7")
        cfg = load_config(str(p))
        assert cfg.server.port == 9999
        assert cfg.queue.max_queue_size == 42
        assert cfg.server.host == "1.2.3.4"
        assert cfg.queue.worker.max_concurrent == 7

    def test_unknown_strategy_rejected(self):
        # The reference silently falls back on unknown strategy names
        # (scheduler.go:105-107, load_balancer.go:272-274); we raise.
        from llmq_tpu.core.config import LoadBalancerConfig
        with pytest.raises(ValueError):
            LoadBalancerConfig(strategy="weighted_round_robin")

    def test_unknown_key_rejected(self, tmp_path):
        p = tmp_path / "c.yaml"
        p.write_text("serverr: {port: 1}\n")
        with pytest.raises(ValueError):
            load_config(str(p))

    def test_repo_canonical_config_loads(self):
        path = os.path.join(os.path.dirname(__file__), "..", "configs", "config.yaml")
        cfg = load_config(path, env=False)
        assert isinstance(cfg, Config)
        assert cfg.loadbalancer.strategy == "adaptive_load"


class TestFakeClock:
    def test_advance(self):
        clk = FakeClock(start=100.0)
        assert clk.now() == 100.0
        clk.advance(5.0)
        assert clk.now() == 105.0

    def test_callbacks(self):
        clk = FakeClock(start=0.0)
        fired = []
        clk.call_at(10.0, lambda: fired.append(1))
        clk.advance(5.0)
        assert not fired
        clk.advance(5.0)
        assert fired == [1]
