"""Critical-path plane (observability/critical_path.py,
docs/observability.md "Critical path & boot telemetry"): the
per-request segment decomposition and its CONSERVATION invariant —
the segments tile the recorded end-to-end duration within 2 % — on
echo and CPU-JAX engines including chaos traffic (crash recovery,
cancellation, preempt/shed) and the 2-deep async pipeline; the
replica-boot decomposition (``replica_ready_seconds{stage}``) pinned
for all three ReplicaPool kinds; the flight-recorder retention fix
(a breach detected at scrape time re-retains the evicted timeline);
the hard off-switch; and the < 3 % hot-path overhead guard."""

from __future__ import annotations

import time

import pytest

from llmq_tpu.core.types import Priority
from llmq_tpu.engine.engine import GenRequest, InferenceEngine
from llmq_tpu.engine.executor import EchoExecutor
from llmq_tpu.engine.tokenizer import ByteTokenizer
from llmq_tpu.observability import critical_path as cp_mod
from llmq_tpu.observability.critical_path import (BOOT_STAGES, SEGMENTS,
                                                  BootRegistry,
                                                  CriticalPathAnalyzer,
                                                  decompose,
                                                  get_boot_registry,
                                                  get_critical_path)
from llmq_tpu.observability.recorder import (FlightRecorder, Timeline,
                                             TraceEvent, get_recorder)

pytestmark = [pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")]


@pytest.fixture(autouse=True)
def _clean_cp():
    rec = get_recorder()
    # Drain tuples other tests left pending BEFORE clearing the
    # analyzer, or they would feed our cleared rollup mid-test.
    rec.flush_metrics()
    ana = get_critical_path()
    ana.clear()
    ana.reconfigure(enabled=True, recent_capacity=256)
    get_boot_registry().clear()
    yield
    rec.flush_metrics()
    ana.clear()
    ana.reconfigure(enabled=True, recent_capacity=256)
    get_boot_registry().clear()


def make_echo_engine(name="cp-echo", slots=4, chunk=4, **kw):
    tok = ByteTokenizer()
    ex = EchoExecutor(batch_size=slots, page_size=8, num_pages=256,
                      max_pages_per_seq=16, eos_id=tok.eos_id,
                      chunk_size=chunk, mixed_prefill_slices=2,
                      mixed_slice_tokens=8)
    return InferenceEngine(ex, tok, name=name, enable_metrics=False,
                           max_decode_steps=64, **kw)


def tl_of(events, rid="r1"):
    """Timeline from (stage, ts[, meta]) tuples on one host."""
    tl = Timeline(rid)
    for ev in events:
        stage, ts = ev[0], ev[1]
        meta = ev[2] if len(ev) > 2 else None
        tl.events.append(TraceEvent(stage, ts, "h0", meta))
    return tl


def _conserved(segments, total_s, rel=1e-9):
    return sum(segments.values()) == pytest.approx(total_s, rel=rel,
                                                   abs=1e-9)


# -- decompose(): pure segment decomposition -----------------------------------


class TestDecompose:
    def test_full_lifecycle_tiles_exactly(self):
        d = decompose(tl_of([
            ("enqueued", 0.0, {"priority": "high"}),
            ("scheduled", 1.0), ("dispatched", 1.5), ("admitted", 2.0),
            ("prefill_start", 2.2), ("first_token", 3.0),
            ("decode_done", 5.0), ("completed", 5.5)]))
        assert d is not None
        s = d["segments"]
        assert s["queue_wait"] == pytest.approx(1.0)
        assert s["dispatch"] == pytest.approx(0.5)
        # admitted AND prefill_start both close "admission".
        assert s["admission"] == pytest.approx(0.7)
        assert s["prefill"] == pytest.approx(0.8)
        # No decode_device_s attribution: the whole span is presumed
        # compute — stall must be EVIDENCED, never inferred.
        assert s["decode_compute"] == pytest.approx(2.0)
        assert "decode_stall" not in s
        assert s["completion"] == pytest.approx(0.5)
        assert d["total_s"] == pytest.approx(5.5)
        assert _conserved(s, d["total_s"])
        assert d["dominant"] == "decode_compute"
        assert d["outcome"] == "completed"
        assert d["priority"] == "high"
        assert set(s) <= set(SEGMENTS)

    def test_decode_split_against_device_attribution(self):
        d = decompose(tl_of([
            ("admitted", 0.0), ("first_token", 1.0),
            ("decode_done", 3.0),
            ("completed", 3.1, {"decode_device_s": 1.5})]))
        s = d["segments"]
        assert s["decode_compute"] == pytest.approx(1.5)
        assert s["decode_stall"] == pytest.approx(0.5)
        assert _conserved(s, d["total_s"])

    def test_decode_attribution_clamped_to_span(self):
        # Attributed device time exceeding the wall span (clock noise,
        # over-attribution) must not mint negative stall.
        d = decompose(tl_of([
            ("admitted", 0.0), ("first_token", 1.0),
            ("decode_done", 2.0),
            ("completed", 2.0, {"decode_device_s": 9.9})]))
        s = d["segments"]
        assert s["decode_compute"] == pytest.approx(1.0)
        assert "decode_stall" not in s
        assert _conserved(s, d["total_s"])

    def test_sub_span_carved_out_not_added(self):
        # kv_promote spans [1.6, 1.9] inside dispatch→admitted: the
        # 0.3 s MOVES out of "admission", conservation by construction.
        base = decompose(tl_of([
            ("enqueued", 0.0), ("scheduled", 1.0), ("dispatched", 1.5),
            ("admitted", 2.0), ("first_token", 3.0),
            ("completed", 3.5)]))
        carved = decompose(tl_of([
            ("enqueued", 0.0), ("scheduled", 1.0), ("dispatched", 1.5),
            ("kv_promote_start", 1.6), ("kv_promote_done", 1.9),
            ("admitted", 2.0), ("first_token", 3.0),
            ("completed", 3.5)]))
        assert carved["segments"]["kv_promote"] == pytest.approx(0.3)
        assert carved["segments"]["admission"] == pytest.approx(
            base["segments"]["admission"] - 0.3)
        assert _conserved(carved["segments"], carved["total_s"])
        assert carved["total_s"] == base["total_s"]

    def test_handoff_claim_spanning_multiple_base_intervals(self):
        d = decompose(tl_of([
            ("enqueued", 0.0), ("scheduled", 1.0),
            ("handoff_claim_start", 0.5), ("dispatched", 1.5),
            ("handoff_claim_done", 1.2), ("admitted", 2.0),
            ("first_token", 3.0), ("completed", 3.0)]))
        s = d["segments"]
        # [0.5, 1.2] overlaps queue_wait [0,1] and dispatch [1,1.5].
        assert s["handoff_claim"] == pytest.approx(0.7)
        assert s["queue_wait"] == pytest.approx(0.5)
        assert s["dispatch"] == pytest.approx(0.3)
        assert _conserved(s, d["total_s"])

    def test_clock_skew_clamped_monotone(self):
        # dispatched stamped BEFORE scheduled (cross-host skew): no
        # negative segment, still tiles exactly.
        d = decompose(tl_of([
            ("enqueued", 0.0), ("scheduled", 1.8), ("dispatched", 1.5),
            ("admitted", 2.0), ("first_token", 3.0),
            ("completed", 3.2)]))
        assert all(v > 0 for v in d["segments"].values())
        assert "dispatch" not in d["segments"]   # clamped to zero width
        assert _conserved(d["segments"], d["total_s"])

    def test_early_death_named_by_phase(self):
        # Died in queue.
        d = decompose(tl_of([("enqueued", 0.0), ("failed", 1.0)]))
        assert d["segments"] == {"queue_wait": pytest.approx(1.0)}
        assert d["outcome"] == "failed"
        # Died between scheduled and dispatched.
        d = decompose(tl_of([("enqueued", 0.0), ("scheduled", 1.0),
                             ("failed", 2.0)]))
        assert d["segments"]["dispatch"] == pytest.approx(1.0)
        # Cancelled mid-decode.
        d = decompose(tl_of([("enqueued", 0.0), ("admitted", 0.5),
                             ("first_token", 1.0), ("cancelled", 2.5)]))
        assert d["segments"]["decode_compute"] == pytest.approx(1.5)
        assert d["outcome"] == "cancelled"

    def test_unfinished_and_empty_return_none(self):
        assert decompose(tl_of([("enqueued", 0.0),
                                ("admitted", 1.0)])) is None
        assert decompose(Timeline("empty")) is None


# -- analyzer rollup -----------------------------------------------------------


class TestAnalyzer:
    def test_observe_accumulates_and_snapshots(self):
        ana = CriticalPathAnalyzer(recent_capacity=2)
        for i in range(3):
            ok = ana.observe(tl_of([
                ("enqueued", 0.0), ("scheduled", 1.0),
                ("admitted", 1.5), ("first_token", 2.0),
                ("completed", 4.0)], rid=f"a{i}"))
            assert ok
        snap = ana.snapshot()
        assert snap["requests"] == 3
        assert snap["conservation_failures"] == 0
        assert snap["totals_ms"]["queue_wait"] == pytest.approx(3000.0)
        assert snap["dominant"] == {"decode_compute": 3}
        assert sum(snap["share"].values()) == pytest.approx(1.0,
                                                            abs=0.01)
        assert len(snap["recent"]) == 2        # bounded by capacity
        assert snap["by_priority_ms"]["unknown"]["queue_wait"] \
            == pytest.approx(3000.0)

    def test_disabled_analyzer_observes_nothing(self):
        ana = CriticalPathAnalyzer(enabled=False)
        assert ana.observe(tl_of([("enqueued", 0.0),
                                  ("completed", 1.0)])) is False
        assert ana.requests == 0

    def test_metrics_families_fed(self):
        from llmq_tpu.metrics.registry import REGISTRY
        ana = get_critical_path()
        labels = {"segment": "queue_wait", "priority": "normal"}

        def count():
            return REGISTRY.get_sample_value(
                "llm_queue_critical_path_ms_count", labels) or 0.0

        def dom():
            return REGISTRY.get_sample_value(
                "llm_queue_critical_path_dominant_total",
                {"segment": "queue_wait", "priority": "normal"}) or 0.0

        c0, d0 = count(), dom()
        ana.observe(tl_of([("enqueued", 0.0, {"priority": "normal"}),
                           ("scheduled", 2.0), ("completed", 2.1)]))
        assert count() == c0 + 1
        assert dom() == d0 + 1                 # queue_wait dominated


# -- conservation invariant on real engines ------------------------------------


def _assert_conserved(ana, expect_at_least):
    snap = ana.snapshot(recent=256)
    assert snap["requests"] >= expect_at_least
    assert snap["conservation_failures"] == 0
    assert snap["recent"], "no decompositions reached the rollup"
    for r in snap["recent"]:
        seg_sum = sum(r["segments_ms"].values())
        tol = max(0.02 * r["total_ms"], 0.06)  # 2 % / rounding floor
        assert abs(seg_sum - r["total_ms"]) <= tol, r
    return snap


class TestEchoConservation:
    def test_segments_conserve_e2e_duration(self):
        ana = get_critical_path()
        eng = make_echo_engine("cp-c1")
        hs = [eng.submit(GenRequest(
                  id=f"cp{i}", prompt=f"conserve {i} " * (i + 1),
                  priority=Priority.NORMAL, max_new_tokens=16))
              for i in range(12)]
        eng.run_until_idle()
        assert all(h.result.finish_reason in ("eos", "length")
                   for h in hs)
        get_recorder().flush_metrics()
        snap = _assert_conserved(ana, 12)
        assert snap["totals_ms"].get("decode_compute", 0) > 0
        # The engine carried its per-chunk attribution on the terminal
        # event — the join needs no engine reference at scrape time.
        tl = get_recorder().get("cp3")
        term = [e for e in tl.events if e.stage == "completed"]
        assert term and term[0].meta.get("decode_device_s", 0) > 0

    def test_conservation_with_chaos_crash_and_cancel(self):
        ana = get_critical_path()
        eng = make_echo_engine("cp-c2")
        hs = [eng.submit(GenRequest(
                  id=f"cpx{i}", prompt="chaos conserve " * 4,
                  priority=Priority.NORMAL, max_new_tokens=32))
              for i in range(6)]
        for _ in range(8):
            eng.step()
        hs[0].cancel()
        eng.step()
        eng.step()
        out = eng.recover_after_crash()
        assert out["recovered"] > 0
        get_recorder().flush_metrics()
        snap = _assert_conserved(ana, 1)
        outcomes = {r["outcome"] for r in snap["recent"]}
        assert "cancelled" in outcomes or "failed" in outcomes

    def test_conservation_under_preempt_and_shed(self):
        from llmq_tpu.core.config import MixedBatchConfig
        ana = get_critical_path()
        tok = ByteTokenizer()
        ex = EchoExecutor(batch_size=2, page_size=8, num_pages=14,
                          max_pages_per_seq=16, eos_id=tok.eos_id,
                          chunk_size=4, mixed_prefill_slices=2,
                          mixed_slice_tokens=8)
        eng = InferenceEngine(
            ex, tok, name="cp-shed", enable_metrics=False,
            max_decode_steps=64,
            mixed_batch=MixedBatchConfig(enabled=True,
                                         prefill_token_budget=16,
                                         max_slices=2))
        x = eng.submit(GenRequest(id="cps-x", prompt="x" * 32,
                                  priority=Priority.NORMAL,
                                  max_new_tokens=32))
        low = eng.submit(GenRequest(id="cps-low", prompt="y" * 16,
                                    priority=Priority.LOW,
                                    max_new_tokens=16))
        for _ in range(4):
            eng.step()
        rt = eng.submit(GenRequest(id="cps-rt", prompt="z" * 16,
                                   priority=Priority.REALTIME,
                                   max_new_tokens=16))
        eng.run_until_idle()
        for h in (x, low, rt):
            assert h.result.finish_reason in ("eos", "length")
        get_recorder().flush_metrics()
        _assert_conserved(ana, 3)

    def test_conservation_through_2_deep_async_pipeline(self):
        from llmq_tpu.core.config import AsyncPipelineConfig
        ana = get_critical_path()
        tok = ByteTokenizer()
        ex = EchoExecutor(batch_size=4, page_size=8, num_pages=256,
                          max_pages_per_seq=16, eos_id=tok.eos_id,
                          chunk_size=4, mixed_prefill_slices=2,
                          mixed_slice_tokens=8, async_chunks=True)
        eng = InferenceEngine(
            ex, tok, name="cp-pipe", enable_metrics=False,
            max_decode_steps=64,
            async_pipeline=AsyncPipelineConfig(enabled=True, depth=2,
                                               completion_workers=1))
        hs = [eng.submit(GenRequest(id=f"cpp{i}",
                                    prompt=f"pipeline conserve {i} " * 2,
                                    max_new_tokens=16))
              for i in range(8)]
        eng.run_until_idle()
        eng.stop()                 # drain the completion pool
        assert all(h.result.finish_reason in ("eos", "length")
                   for h in hs)
        get_recorder().flush_metrics()
        snap = _assert_conserved(ana, 8)
        assert snap["totals_ms"].get("decode_compute", 0) > 0


class TestJaxConservation:
    def test_conservation_on_cpu_jax_engine(self):
        import jax

        from llmq_tpu.engine.executor import JaxExecutor
        from llmq_tpu.models.llama import get_config, init_params
        ana = get_critical_path()
        cfg = get_config("llama3-tiny", max_seq_len=256, vocab_size=512)
        params = init_params(jax.random.PRNGKey(0), cfg)
        tok = ByteTokenizer()
        ex = JaxExecutor(cfg, params, batch_size=3, page_size=8,
                         num_pages=96, prefill_buckets=[16, 64],
                         eos_id=tok.eos_id, chunk_size=4)
        eng = InferenceEngine(ex, tok, name="cp-jax",
                              enable_metrics=False, max_decode_steps=12)
        hs = [eng.submit(GenRequest(
                  id=f"cpj{i}", prompt=f"jax conserve {i}",
                  priority=Priority.NORMAL, max_new_tokens=10))
              for i in range(4)]
        for _ in range(3):
            eng.step()
        hs[0].cancel()             # chaos: client went away mid-decode
        eng.run_until_idle()
        assert all(h.done for h in hs)
        get_recorder().flush_metrics()
        snap = _assert_conserved(ana, 3)
        assert snap["totals_ms"].get("decode_compute", 0) > 0


# -- flight-recorder retention fix (satellite) ---------------------------------


class TestScrapeTimeRetention:
    def test_evicted_breach_re_retained_from_carried_copy(self):
        """A failed timeline evicted from BOTH the ring and the slow
        buffer before the scrape drains its tuple must still land in
        slow() (re-retained from the carried copy) AND still reach the
        critical-path join."""
        ana = get_critical_path()
        rec = FlightRecorder(capacity=1, slow_capacity=1, sla_ms=0,
                             emit_metrics=True)
        rec.record("A", "enqueued", ts=100.0, priority="normal")
        rec.record("A", "failed", ts=101.0)
        # B evicts A from the 1-slot ring AND its copy from the 1-slot
        # slow buffer.
        rec.record("B", "enqueued", ts=102.0, priority="normal")
        rec.record("B", "failed", ts=103.0)
        assert rec.get("A") is None or all(
            t.request_id != "A" for t in rec.slow())
        before = ana.requests
        assert rec.flush_metrics() == 2
        assert any(t.request_id == "A" for t in rec.slow())
        assert ana.requests == before + 2


# -- hard off-switch -----------------------------------------------------------


class TestOffSwitch:
    def test_disabled_plane_stamps_and_joins_nothing(self):
        ana = get_critical_path()
        ana.reconfigure(enabled=False)
        eng = make_echo_engine("cp-off")
        h = eng.submit(GenRequest(id="cpoff1", prompt="dark " * 3,
                                  max_new_tokens=6))
        eng.run_until_idle()
        assert h.result.finish_reason in ("eos", "length")
        # No cp-only marks on the handle, no cp meta on the terminal.
        assert "decode_done" not in h.marks
        tl = get_recorder().get("cpoff1")
        assert all(e.stage != "decode_done" for e in tl.events)
        term = [e for e in tl.events if e.stage == "completed"]
        assert term and "decode_device_s" not in term[0].meta
        get_recorder().flush_metrics()
        assert ana.requests == 0
        assert ana.snapshot()["enabled"] is False

    def test_disabled_plane_records_no_boot(self):
        from llmq_tpu.controlplane.pool import LocalEnginePool
        get_critical_path().reconfigure(enabled=False)
        pool = LocalEnginePool(
            lambda seq: make_echo_engine(f"cp-offboot-{seq}"),
            supervise=False)
        ep = pool.provision(0)
        try:
            assert ep is not None
            assert get_boot_registry().snapshot() == {}
        finally:
            pool.stop()

    def test_route_503_when_disabled(self):
        from llmq_tpu.api.server import ApiServer
        from llmq_tpu.core.config import default_config
        get_critical_path().reconfigure(enabled=False)
        api = ApiServer(default_config())
        status, _, _ = api.dispatch(
            "GET", "/api/v1/analysis/critical-path", b"")
        assert status == 503

    def test_config_wiring_and_feed_contract(self):
        from llmq_tpu.core.config import default_config
        from llmq_tpu.observability.recorder import configure
        rec = get_recorder()
        ana = get_critical_path()
        cfg = default_config()
        try:
            cfg.observability.critical_path.enabled = False
            configure(cfg.observability)
            assert ana.enabled is False
            cfg.observability.critical_path.enabled = True
            configure(cfg.observability)
            assert ana.enabled is True
            # Feed contract: the join is FED by the recorder's metrics
            # flush — trace plane off force-disables the analyzer.
            cfg.observability.emit_metrics = False
            configure(cfg.observability)
            assert ana.enabled is False
        finally:
            cfg.observability.emit_metrics = True
            cfg.observability.critical_path.enabled = True
            configure(cfg.observability)
            rec.reconfigure(enabled=True)
            assert ana.enabled is True


# -- replica boot decomposition ------------------------------------------------


class TestBootRegistry:
    def test_begin_stage_ready_roundtrip(self):
        reg = BootRegistry()
        reg.begin("r0", "local")
        reg.stage("r0", "weights", 1.0)
        reg.stage("r0", "weights", 0.5)       # accumulates
        reg.stage("r0", "compile", 2.0)
        reg.stage("r0", "nonsense", 9.0)      # unknown stage ignored
        reg.stage("r0", "warmup", -1.0)       # negative ignored
        reg.ready("r0", total_s=4.0)
        rec = reg.get("r0")
        assert rec["ready"] is True
        assert rec["total_s"] == pytest.approx(4.0)
        assert rec["stages_s"] == {"weights": pytest.approx(1.5),
                                   "compile": pytest.approx(2.0)}

    def test_adopt_makes_stages_sum_to_ready_wall(self):
        reg = BootRegistry()
        reg.adopt("child-1", "subprocess",
                  {"weights": 1.0, "compile": 2.5, "warmup": 0.5,
                   "bogus": 9.0}, total_s=5.0)
        rec = reg.get("child-1")
        assert rec["ready"] is True
        # provision = ready wall minus the child-stamped stages.
        assert rec["stages_s"]["provision"] == pytest.approx(1.0)
        assert sum(rec["stages_s"].values()) == pytest.approx(5.0)
        assert set(rec["stages_s"]) <= set(BOOT_STAGES)

    def test_adopt_without_child_stages_is_all_provision(self):
        reg = BootRegistry()
        reg.adopt("child-2", "exec", {}, total_s=3.0)
        rec = reg.get("child-2")
        assert rec["stages_s"] == {"provision": pytest.approx(3.0)}

    def test_capacity_bound_evicts_oldest(self):
        reg = BootRegistry(capacity=2)
        for i in range(4):
            reg.begin(f"b{i}", "local")
        snap = reg.snapshot()
        assert set(snap) == {"b2", "b3"}

    def test_flush_feeds_replica_ready_seconds(self):
        from llmq_tpu.metrics.registry import REGISTRY
        reg = get_boot_registry()

        def count(stage):
            return REGISTRY.get_sample_value(
                "llm_queue_replica_ready_seconds_count",
                {"stage": stage}) or 0.0

        c0 = count("compile")
        reg.begin("fl0", "local")
        reg.stage("fl0", "compile", 2.0)
        assert reg.flush() >= 1
        assert count("compile") == c0 + 1

    def test_first_token_closes_the_process_record(self):
        cp_mod.boot_begin("proc-1", "engine", process=True)
        cp_mod.boot_stage("proc-1", "weights", 0.01)
        cp_mod.note_first_token()
        rec = get_boot_registry().get("proc-1")
        assert "first_token" in rec["stages_s"]
        first = rec["stages_s"]["first_token"]
        cp_mod.note_first_token()              # idempotent
        assert get_boot_registry().get(
            "proc-1")["stages_s"]["first_token"] == first


class TestPoolBoot:
    def test_local_pool_records_boot_decomposition(self):
        from llmq_tpu.controlplane.pool import LocalEnginePool
        pool = LocalEnginePool(
            lambda seq: make_echo_engine(f"cp-boot-{seq}"),
            supervise=False)
        ep = pool.provision(0)
        try:
            assert ep is not None
            assert ep.metadata["boot_id"] == "local-0"
            rec = get_boot_registry().get("local-0")
            assert rec is not None and rec["ready"] is True
            assert rec["total_s"] > 0
            assert rec["stages_s"].get("provision", 0) > 0
            assert sum(rec["stages_s"].values()) == pytest.approx(
                rec["total_s"], rel=0.02, abs=0.005)
            # The first committed token closes the decomposition.
            eng = ep.metadata["engine"]
            h = eng.submit(GenRequest(id="cpb0", prompt="boot token",
                                      max_new_tokens=4))
            eng.run_until_idle()
            assert h.result.finish_reason in ("eos", "length")
            rec = get_boot_registry().get("local-0")
            assert rec["stages_s"].get("first_token", -1) >= 0
        finally:
            pool.stop()

    def test_exec_pool_adopts_child_boot_block(self):
        import json
        import threading
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        from llmq_tpu.controlplane.pool import ExecReplicaPool
        from llmq_tpu.core.config import ReplicaPoolConfig

        body = json.dumps({"status": "ok", "boot": {
            "stages_s": {"weights": 1.25, "compile": 3.5}}}).encode()

        class _Health(BaseHTTPRequestHandler):
            def do_GET(self):
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Health)
        port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        try:
            pool = ExecReplicaPool(ReplicaPoolConfig(
                kind="exec",
                provision_cmd=f"echo http://127.0.0.1:{port}",
                ready_timeout=5.0))
            ep = pool.provision(7)
            assert ep is not None
            rec = get_boot_registry().get(f"127.0.0.1:{port}")
            assert rec is not None and rec["ready"] is True
            assert rec["kind"] == "exec"
            assert rec["total_s"] > 0
            # Child stages adopted verbatim across the pool seam.
            assert rec["stages_s"]["weights"] == pytest.approx(1.25)
            assert rec["stages_s"]["compile"] == pytest.approx(3.5)
            assert "provision" in rec["stages_s"]
        finally:
            httpd.shutdown()

    def test_subprocess_pool_adopts_real_replica_boot(self):
        """One real ``python -m llmq_tpu serve`` echo replica: the
        pool adopts the child's /health boot block, provision covers
        spawn + rendezvous, and the stages sum to the ready wall."""
        import socket

        from llmq_tpu.controlplane.pool import SubprocessReplicaPool
        from llmq_tpu.core.config import ReplicaPoolConfig
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            base = s.getsockname()[1]
        pool = SubprocessReplicaPool(ReplicaPoolConfig(
            kind="subprocess", base_port=base,
            args=["--backend", "echo"], ready_timeout=45.0))
        ep = pool.provision(0)
        try:
            assert ep is not None, "replica never became ready"
            rec = get_boot_registry().get(ep.id)
            assert rec is not None and rec["ready"] is True
            assert rec["kind"] == "subprocess"
            assert rec["total_s"] > 0
            assert rec["stages_s"].get("provision", 0) > 0
            assert sum(rec["stages_s"].values()) == pytest.approx(
                rec["total_s"], rel=0.02, abs=0.01)
        finally:
            pool.stop()


# -- API surface ---------------------------------------------------------------


class TestApiRoutes:
    def test_analysis_route_serves_rollup_and_boot(self):
        from llmq_tpu.api.server import ApiServer
        from llmq_tpu.core.config import default_config
        eng = make_echo_engine("cp-api")
        hs = [eng.submit(GenRequest(id=f"cpa{i}", prompt="api",
                                    max_new_tokens=4))
              for i in range(3)]
        eng.run_until_idle()
        assert all(h.done for h in hs)
        get_boot_registry().adopt("api-child", "exec", {}, total_s=1.0)
        api = ApiServer(default_config(), engine=eng)
        status, payload, _ = api.dispatch(
            "GET", "/api/v1/analysis/critical-path?recent=2", b"")
        assert status == 200
        assert payload["requests"] >= 3
        assert payload["conservation_failures"] == 0
        assert len(payload["recent"]) <= 2
        assert payload["totals_ms"]
        assert payload["boot"]["api-child"]["ready"] is True

    def test_trace_route_attaches_decomposition(self):
        from llmq_tpu.api.server import ApiServer
        from llmq_tpu.core.config import default_config
        eng = make_echo_engine("cp-tr")
        h = eng.submit(GenRequest(id="cptr0", prompt="trace me " * 2,
                                  max_new_tokens=6))
        eng.run_until_idle()
        assert h.done
        api = ApiServer(default_config(), engine=eng)
        status, payload, _ = api.dispatch(
            "GET", "/api/v1/requests/cptr0/trace", b"")
        assert status == 200
        cp = payload["critical_path"]
        assert cp["segments"]
        assert sum(cp["segments"].values()) == pytest.approx(
            cp["total_s"], rel=0.02, abs=1e-4)


# -- overhead guard (acceptance criterion: < 3 % on the hot path) --------------


class TestOverheadGuard:
    def test_cp_hot_path_additions_under_3pct_of_echo_request(self):
        """The plane's ENTIRE hot-path footprint is: one float
        accumulate per decode row per chunk, and at finish two
        perf_counter marks + a dict setdefault + a round(). Measure one
        echo request end-to-end, micro-measure those ops, and require
        chunks x per-chunk + finish cost < 3 % of the request
        (deterministic decomposition, mirroring the PR-3/PR-6 guards —
        wall-clock A/B noise on shared CI exceeds 3 %)."""
        eng = make_echo_engine("cp-oh", chunk=1)
        n, max_new = 24, 16
        t0 = time.perf_counter()
        hs = [eng.submit(GenRequest(id=f"cpoh{i}",
                                    prompt="overhead " * 2,
                                    max_new_tokens=max_new))
              for i in range(n)]
        eng.run_until_idle()
        assert all(h.done for h in hs)
        per_request = (time.perf_counter() - t0) / n
        chunks_per_request = (
            eng.get_stats()["device"]["steps"]["count"] / n)

        acc = 0.0
        marks = {}
        per_op = float("inf")
        for _ in range(5):
            m = 20000
            t0 = time.perf_counter()
            for i in range(m):
                # per-chunk: weighted share accumulate; per-finish:
                # mark + setdefault + round (amortized into the loop).
                acc += 1e-4 * (4 / 7)
                marks.setdefault(i & 7, time.perf_counter())
                round(acc, 6)
            per_op = min(per_op, (time.perf_counter() - t0) / m)
        cost = (chunks_per_request + 2) * per_op
        assert cost < 0.03 * per_request, (
            f"critical-path stamping {cost * 1e6:.1f}us/request "
            f"({chunks_per_request:.1f} chunks x {per_op * 1e6:.2f}us)"
            f" vs request {per_request * 1e6:.1f}us")
