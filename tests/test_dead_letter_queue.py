"""DeadLetterQueue tests.

Mirrors reference tests/priorityqueue_test.go:569-698 (push/get/requeue/
batch-requeue) plus bounded-eviction and handler-failure coverage."""

import pytest

from llmq_tpu.core.errors import MessageNotFoundError
from llmq_tpu.core.types import Message, MessageStatus
from llmq_tpu.queueing.dead_letter_queue import DeadLetterQueue
from llmq_tpu.queueing.queue_manager import QueueManager


@pytest.fixture
def dlq(fake_clock) -> DeadLetterQueue:
    return DeadLetterQueue(max_size=3, clock=fake_clock)


class TestPush:
    def test_push_and_get(self, dlq, fake_clock):
        m = Message(content="dead")
        m.retry_count = 3
        item = dlq.push(m, "kept failing", "normal")
        assert item.retry_count == 3
        assert item.failed_at == fake_clock.now()
        got = dlq.get(m.id)
        assert got.message.content == "dead"
        assert got.source_queue == "normal"

    def test_get_missing_raises(self, dlq):
        with pytest.raises(MessageNotFoundError):
            dlq.get("nope")

    def test_bounded_evicts_oldest(self, dlq):
        ms = [Message(content=f"m{i}") for i in range(4)]
        for m in ms:
            dlq.push(m, "r", "q")
        assert dlq.size() == 3
        with pytest.raises(MessageNotFoundError):
            dlq.get(ms[0].id)  # oldest evicted
        assert dlq.get(ms[3].id)

    def test_handlers_invoked(self, dlq):
        seen = []
        dlq.add_handler(lambda item: seen.append(item.message.id))
        m = Message()
        dlq.push(m, "r", "q")
        assert seen == [m.id]

    def test_handler_error_swallowed(self, dlq):
        def bad(item):
            raise RuntimeError("handler broke")
        dlq.add_handler(bad)
        m = Message()
        dlq.push(m, "r", "q")  # no raise
        assert dlq.size() == 1


class TestRequeue:
    def test_requeue_resets_state(self, dlq, fake_clock, queue_backend):
        qm = QueueManager("t", clock=fake_clock, backend=queue_backend,
                          enable_metrics=False)
        m = Message(content="retry me")
        m.retry_count = 3
        m.status = MessageStatus.FAILED
        m.error = "boom"
        dlq.push(m, "boom", "normal")
        back = dlq.requeue(m.id, qm)
        assert back.retry_count == 0
        assert back.status == MessageStatus.PENDING
        assert back.error == ""
        assert qm.queue.size("normal") == 1
        assert dlq.size() == 0

    def test_batch_requeue_all(self, dlq, fake_clock, queue_backend):
        qm = QueueManager("t", clock=fake_clock, backend=queue_backend,
                          enable_metrics=False)
        for i in range(3):
            dlq.push(Message(content=f"m{i}"), "r", "low")
        out = dlq.batch_requeue(qm)
        assert len(out) == 3
        assert qm.queue.size("low") == 3
        assert dlq.size() == 0

    def test_clear(self, dlq):
        dlq.push(Message(), "r", "q")
        assert dlq.clear() == 1
        assert dlq.size() == 0


class TestHandlerIsolation:
    """Satellite: a raising handler must not abort push, must not skip
    the remaining handlers, and must be COUNTED
    (dlq_handler_errors_total)."""

    def test_raising_handler_isolated_and_counted(self):
        from llmq_tpu.metrics.registry import get_metrics
        dlq = DeadLetterQueue(max_size=10, name="handler-iso")
        seen = []

        def bad(item):
            raise RuntimeError("alerting hook exploded")

        def good(item):
            seen.append(item.message.id)

        dlq.add_handler(bad)
        dlq.add_handler(good)
        metric = get_metrics().dlq_handler_errors.labels("handler-iso")
        before = metric._value.get()
        msg = Message(id="h1", content="x", user_id="u")
        item = dlq.push(msg, "boom", "normal")   # must NOT raise
        assert item.message.id == "h1"
        assert seen == ["h1"]                    # later handler still ran
        assert dlq.size() == 1                   # stored despite the raise
        assert metric._value.get() == before + 1
