"""DelayedQueue tests.

Mirrors reference tests/priorityqueue_test.go:471-567 (delayed delivery
timing) — with a fake clock, so "elapsed >= delay" is exact instead of
sleep-based."""

import threading

from llmq_tpu.core.types import Message
from llmq_tpu.queueing.delayed_queue import DelayedQueue


class TestScheduling:
    def test_not_delivered_early(self, fake_clock):
        out = []
        dq = DelayedQueue(lambda q, m: out.append((q, m)), clock=fake_clock)
        dq.schedule_after(Message(content="a"), 5.0, "normal")
        assert dq.run_due_once() == 0
        fake_clock.advance(4.99)
        assert dq.run_due_once() == 0
        fake_clock.advance(0.02)
        assert dq.run_due_once() == 1
        assert out[0][0] == "normal"

    def test_delivery_order_by_ready_time(self, fake_clock):
        out = []
        dq = DelayedQueue(lambda q, m: out.append(m.content), clock=fake_clock)
        dq.schedule_after(Message(content="later"), 10.0)
        dq.schedule_after(Message(content="sooner"), 1.0)
        assert dq.peek().content == "sooner"
        assert dq.next_ready_at() == fake_clock.now() + 1.0
        fake_clock.advance(20.0)
        dq.run_due_once()
        assert out == ["sooner", "later"]

    def test_schedule_sets_scheduled_at(self, fake_clock):
        dq = DelayedQueue(lambda q, m: None, clock=fake_clock)
        m = Message()
        dq.schedule(m, 123.0)
        assert m.scheduled_at == 123.0

    def test_size(self, fake_clock):
        dq = DelayedQueue(lambda q, m: None, clock=fake_clock)
        assert dq.size() == 0
        dq.schedule_after(Message(), 1.0)
        assert dq.size() == 1

    def test_delivery_failure_does_not_stop_others(self, fake_clock):
        out = []

        def deliver(q, m):
            if m.content == "boom":
                raise RuntimeError("handler broke")
            out.append(m.content)

        dq = DelayedQueue(deliver, clock=fake_clock)
        dq.schedule_after(Message(content="boom"), 1.0)
        dq.schedule_after(Message(content="ok"), 1.0)
        fake_clock.advance(2.0)
        assert dq.run_due_once() == 2
        assert out == ["ok"]


class TestRunLoop:
    def test_real_time_loop(self):
        # Real-clock smoke test of the timer loop + re-arm on earlier item
        # (delayed_queue.go:114-199).
        delivered = threading.Event()
        dq = DelayedQueue(lambda q, m: delivered.set())
        dq.start()
        try:
            dq.schedule_after(Message(), 0.05)
            assert delivered.wait(timeout=5.0)
        finally:
            dq.stop()
