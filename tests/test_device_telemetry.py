"""Device telemetry plane (llmq_tpu/observability/device.py,
docs/observability.md "Device telemetry"): step-time decomposition
through the echo and JAX serving paths, the shared MFU/RTT math bench
uses, HBM accounting, compile/export-cache visibility, SLO burn rates —
and the <3 % step-path overhead guard the acceptance criterion sets."""

from __future__ import annotations

import time

import pytest

import jax

from llmq_tpu.core.config import SloConfig, default_config
from llmq_tpu.engine import ByteTokenizer, EchoExecutor, InferenceEngine
from llmq_tpu.engine.engine import GenRequest
from llmq_tpu.engine.kv_allocator import PageAllocator
from llmq_tpu.metrics.registry import REGISTRY
from llmq_tpu.observability.device import (DeviceTelemetry, decode_mfu,
                                           get_device_telemetry,
                                           measure_rtt, peak_flops)
from llmq_tpu.observability.slo import (SloTracker, configure_slo,
                                        get_slo_tracker, window_label)


def _echo_engine(name, *, chunk=4, metrics=True, batch=4):
    eng = InferenceEngine(
        EchoExecutor(batch_size=batch, chunk_size=chunk),
        ByteTokenizer(), name=name, enable_metrics=metrics)
    return eng


def _serve(eng, n=6, prompt="device telemetry", max_new=12):
    handles = [eng.submit(GenRequest(id=f"{eng.name}-{i}", prompt=prompt,
                                     max_new_tokens=max_new))
               for i in range(n)]
    eng.run_until_idle()
    assert all(h.result.finish_reason in ("eos", "length")
               for h in handles), [h.result for h in handles]
    return handles


# -- shared math (the bench dedup satellite) ----------------------------------

class TestSharedMath:
    def test_peak_flops_table(self):
        assert peak_flops("TPU v5e") == 197e12
        assert peak_flops("TPU v5p") == 459e12
        assert peak_flops("unknown-device") == 197e12  # bench fallback

    def test_int8_doubles_peak(self):
        assert peak_flops("TPU v5e", quant="int8") == 2 * 197e12

    def test_decode_mfu_formula(self):
        # 1000 tok/s on a 1B model: 2e12 FLOP/s of 197e12 peak.
        assert decode_mfu(1000, 10**9, "v5e") == pytest.approx(
            2e12 / 197e12)
        assert decode_mfu(0, 10**9, "v5e") == 0.0
        assert decode_mfu(1000, 0, "v5e") == 0.0   # echo: no params

    def test_measure_rtt_on_cpu(self):
        rtt = measure_rtt(samples=3)
        assert 0 < rtt < 5000

    def test_decode_hbm_bw_util_formula(self):
        from llmq_tpu.observability.device import (decode_hbm_bw_util,
                                                   peak_hbm_bandwidth)
        assert peak_hbm_bandwidth("TPU v5e") == 819e9
        assert peak_hbm_bandwidth("unknown") == 819e9
        # 64 rows at 6400 tok/s = 100 steps/s; 2 GB weights + 64 rows
        # × 100 KB/token × 512 tokens of live KV per step.
        got = decode_hbm_bw_util(6400, 64, 2 * 10**9, 100_000, 512,
                                 "v5e")
        want = 100 * (2 * 10**9 + 64 * 100_000 * 512) / 819e9
        assert got == pytest.approx(want)
        assert decode_hbm_bw_util(0, 64, 1, 1, 1, "v5e") == 0.0


# -- step decomposition through the serving path ------------------------------

class TestStepDecomposition:
    def test_echo_sync_path_populates_all_three_legs(self):
        eng = _echo_engine("dev-echo")
        _serve(eng)
        dev = eng.get_stats()["device"]
        steps = dev["steps"]
        assert steps["count"] > 0
        # Sync path: every leg observed once per chunk, device leg
        # carries the executor call.
        for leg in ("dispatch_ms", "device_ms", "readback_ms"):
            assert steps[leg]["count"] == steps["count"]
        assert steps["device_ms"]["total_ms"] > 0
        assert dev["tokens_total"] > 0
        assert dev["decode_tokens_per_s"] > 0
        # Echo has no params → MFU pins to 0 rather than lying.
        assert dev["mfu_pct"] == 0.0

    def test_step_histograms_exported_with_engine_label(self):
        eng = _echo_engine("dev-metrics")
        _serve(eng)
        from llmq_tpu.metrics.registry import exposition
        exp = exposition().decode()
        for fam in ("llm_queue_step_dispatch_ms_count",
                    "llm_queue_step_device_ms_count",
                    "llm_queue_step_readback_ms_count"):
            assert f'{fam}{{engine="dev-metrics"}}' in exp, fam
        assert REGISTRY.get_sample_value(
            "llm_queue_step_device_ms_count",
            {"engine": "dev-metrics"}) > 0
        # Scrape-time gauges refreshed by the exposition flush.
        assert REGISTRY.get_sample_value(
            "llm_queue_decode_tokens_per_s",
            {"engine": "dev-metrics"}) > 0

    def test_metrics_off_engine_still_tracks_host_side(self):
        # Bench engines run with enable_metrics=False yet read
        # per-rate-point device telemetry from get_stats.
        eng = _echo_engine("dev-nometrics", metrics=False)
        _serve(eng)
        dev = eng.get_stats()["device"]
        assert dev["steps"]["count"] > 0
        assert dev["tokens_total"] > 0

    def test_mixed_path_notes_steps(self):
        cfg = default_config()
        cfg.executor.decode_chunk = 4
        cfg.executor.mixed_batch.prefill_token_budget = 32
        from llmq_tpu.engine import build_engine
        eng = build_engine(cfg, name="dev-mixed", enable_metrics=False)
        _serve(eng, n=8, prompt="mixed telemetry " * 4)
        stats = eng.get_stats()
        assert stats["mixed_batch"]["steps"] > 0
        assert stats["device"]["steps"]["count"] > 0


# -- HBM accounting ------------------------------------------------------------

class TestHbmAccounting:
    def test_allocator_fragmentation(self):
        alloc = PageAllocator(17, 16)
        pages = alloc.alloc(12)
        assert alloc.fragmentation() == 0.0       # one contiguous run
        # Free every other page: the free space is maximally interleaved.
        alloc.free(pages[::2])
        assert alloc.fragmentation() > 0.4
        alloc.free(pages[1::2])
        assert alloc.fragmentation() == 0.0       # whole pool free again

    def test_engine_hbm_snapshot(self):
        eng = _echo_engine("dev-hbm")
        _serve(eng, n=2, prompt="hold pages",
               max_new=4)
        hbm = eng._hbm_snapshot()
        assert hbm["kv_pages_total"] > 0
        assert 0.0 <= hbm["kv_pool_occupancy"] <= 1.0
        assert 0.0 <= hbm["kv_pool_fragmentation"] <= 1.0
        assert "prefix_cache_pages" in hbm

    def test_occupancy_gauge_set_at_scrape(self):
        eng = _echo_engine("dev-hbm-gauge")
        _serve(eng, n=2)
        from llmq_tpu.metrics.registry import exposition
        exposition()
        val = REGISTRY.get_sample_value(
            "llm_queue_kv_pool_occupancy", {"engine": "dev-hbm-gauge"})
        assert val is not None and 0.0 <= val <= 1.0


# -- JAX executor: compile telemetry + per-chip HBM + pipelined split ---------

def _tiny_executor(name, **kw):
    from llmq_tpu.engine.executor import JaxExecutor
    from llmq_tpu.models.llama import init_params, llama3_tiny
    cfg = llama3_tiny(max_seq_len=128)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return JaxExecutor(cfg, params, batch_size=4, page_size=16,
                       num_pages=33, chunk_size=4,
                       prefill_buckets=[16, 32], eos_id=-1,
                       telemetry_name=name, **kw)


class TestJaxTelemetry:
    def test_warmup_compile_and_export_cache_telemetry(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("LLMQ_EXPORT_CACHE_DIR", str(tmp_path))
        ex = _tiny_executor("dev-jax-cold")
        ex.warmup()
        snap = get_device_telemetry("dev-jax-cold").snapshot()
        comp = snap["compile"]
        # Cold start: every program was a cache miss, each with a
        # recorded compile time; warmup progress completed.
        assert comp["cache_misses"] >= len(ex._aot) > 0
        assert comp["cache_hits"] == 0
        assert set(comp["programs"]) == set(ex._aot)
        assert all(p["seconds"] > 0 for p in comp["programs"].values())
        assert comp["warmup_done"] == comp["warmup_total"]
        assert snap["host_device_rtt_ms"] is not None
        # Model identity feeds the MFU estimator.
        assert snap["model"]["n_params"] > 0

        # Warm restart: the export cache serves every program — hits.
        ex2 = _tiny_executor("dev-jax-warm")
        ex2.warmup()
        comp2 = get_device_telemetry("dev-jax-warm").snapshot()["compile"]
        assert comp2["cache_hits"] > 0
        srcs = {p["source"] for p in comp2["programs"].values()}
        assert "export_cache" in srcs

    def test_ragged_warmup_compiles_strictly_fewer_programs(self):
        """Ragged attention collapses the bucket grid: warmup with
        ragged ON must report strictly fewer compile_seconds{program}
        entries than the bucket-grid warmup of the same geometry, with
        the ragged program present and no per-bucket entries."""
        ex_b = _tiny_executor("dev-jax-bucket", mixed_prefill_slices=2,
                              mixed_slice_tokens=8)
        ex_b.warmup()
        ex_r = _tiny_executor("dev-jax-ragged", mixed_prefill_slices=2,
                              mixed_slice_tokens=8, ragged_attention=True,
                              ragged_token_capacity=16)
        ex_r.warmup()
        progs_b = get_device_telemetry(
            "dev-jax-bucket").snapshot()["compile"]["programs"]
        progs_r = get_device_telemetry(
            "dev-jax-ragged").snapshot()["compile"]["programs"]
        assert len(progs_r) < len(progs_b), (progs_r, progs_b)
        assert "ragged_chunk" in progs_r
        assert not any(p.startswith("prefill") for p in progs_r)
        assert any(p.startswith("prefill") for p in progs_b)

    def test_stale_bucket_export_misses_ragged_key(self, tmp_path,
                                                   monkeypatch):
        """The export-cache key includes the ragged geometry: a disk
        cache populated by the bucket grid must MISS for the ragged
        executor (every ragged program re-lowered, zero hits)."""
        monkeypatch.setenv("LLMQ_EXPORT_CACHE_DIR", str(tmp_path))
        ex_b = _tiny_executor("dev-jax-exp-bucket")
        ex_b.warmup()
        assert ex_b._export_cache_key() != _tiny_executor(
            "dev-jax-exp-key", ragged_attention=True)._export_cache_key()
        ex_r = _tiny_executor("dev-jax-exp-ragged",
                              ragged_attention=True)
        ex_r.warmup()
        comp = get_device_telemetry(
            "dev-jax-exp-ragged").snapshot()["compile"]
        assert comp["cache_hits"] == 0
        assert not ex_r._from_export_cache

    def test_hbm_info_reports_resident_bytes(self):
        ex = _tiny_executor("dev-jax-hbm")
        chips = ex.hbm_info()
        assert len(chips) >= 1
        c0 = chips[0]
        assert c0["weights_bytes"] > 0
        assert c0["kv_pool_bytes"] > 0

    def test_pipelined_engine_splits_device_and_readback(self):
        ex = _tiny_executor("dev-jax-pipe")
        eng = InferenceEngine(ex, ByteTokenizer(), name="dev-jax-pipe",
                              max_decode_steps=6, enable_metrics=False)
        _serve(eng, n=3, prompt="ab", max_new=4)
        dev = eng.get_stats()["device"]
        steps = dev["steps"]
        assert steps["count"] > 0
        # Pipelined fetch records all three legs per chunk.
        assert steps["dispatch_ms"]["count"] == steps["count"]
        assert steps["device_ms"]["count"] == steps["count"]
        assert steps["readback_ms"]["count"] == steps["count"]


# -- SLO burn rates ------------------------------------------------------------

class TestSlo:
    def test_window_labels(self):
        assert window_label(300) == "5m"
        assert window_label(3600) == "1h"
        assert window_label(90) == "90s"

    def test_burn_rate_math(self):
        t = SloTracker(targets={"ttft": 100.0}, objective=0.99,
                       windows_s=(300.0,), metrics=False)
        for _ in range(98):
            t.observe("ttft", 50.0)
        for _ in range(2):
            t.observe("ttft", 500.0)
        rates = t.burn_rates()["ttft"]["5m"]
        # 2 % breaches against a 1 % budget → burn rate 2.0.
        assert rates["burn_rate"] == pytest.approx(2.0)
        assert rates["requests"] == 100 and rates["breaches"] == 2

    def test_zero_traffic_burns_nothing(self):
        t = SloTracker(targets={"ttft": 100.0}, metrics=False)
        assert t.burn_rates()["ttft"]["5m"]["burn_rate"] == 0.0

    def test_flush_sets_gauges(self):
        t = get_slo_tracker()
        configure_slo(SloConfig())
        t.observe("realtime", 10_000.0)    # one breach
        t.flush()
        v = REGISTRY.get_sample_value(
            "llm_queue_slo_burn_rate", {"slo": "realtime", "window": "5m"})
        assert v is not None and v > 0
        rem = REGISTRY.get_sample_value(
            "llm_queue_slo_error_budget_remaining", {"slo": "realtime"})
        assert rem is not None and 0.0 <= rem <= 1.0

    def test_recorder_feeds_slo_tracker(self):
        from llmq_tpu.observability.recorder import FlightRecorder
        configure_slo(SloConfig(ttft_p99_ms=50.0, realtime_p99_ms=50.0))
        tracker = get_slo_tracker()
        before = tracker.burn_rates()["ttft"]["5m"]["requests"]
        rec = FlightRecorder(capacity=16, emit_metrics=True)
        t0 = time.time()
        rec.record("slo-req-1", "enqueued", ts=t0, priority="realtime")
        rec.record("slo-req-1", "first_token", ts=t0 + 0.2)
        rec.record("slo-req-1", "completed", ts=t0 + 0.4,
                   completion_tokens=3)
        rec.flush_metrics()
        rates = tracker.burn_rates()
        assert rates["ttft"]["5m"]["requests"] > before
        # 200 ms TTFT and 400 ms e2e against 50 ms targets: breaches.
        assert rates["ttft"]["5m"]["breaches"] >= 1
        assert rates["realtime"]["5m"]["breaches"] >= 1

    def test_disabled_slo_config_clears_targets(self):
        tracker = configure_slo(SloConfig(enabled=False))
        assert tracker.targets == {}
        tracker.observe("ttft", 10.0)       # no-op, must not raise
        assert tracker.burn_rates() == {}
        configure_slo(SloConfig())          # restore for other tests

    def test_slo_force_disabled_when_trace_plane_off(self):
        # The tracker is FED by the recorder's flush: with the trace
        # plane off it would report 0 burn forever — configure() must
        # disable it visibly instead (no targets in snapshots).
        from llmq_tpu.core.config import ObservabilityConfig
        from llmq_tpu.observability.recorder import configure
        try:
            configure(ObservabilityConfig(enabled=False))
            assert get_slo_tracker().targets == {}
        finally:
            configure(ObservabilityConfig())    # restore
        assert get_slo_tracker().targets       # fed again


# -- cluster overview rollup ---------------------------------------------------

class TestClusterOverview:
    def test_local_rollup_aggregates_device_blocks(self):
        from llmq_tpu.cluster.router import ClusterRouter
        from llmq_tpu.core.config import ClusterConfig
        from llmq_tpu.loadbalancer.load_balancer import LoadBalancer
        eng = _echo_engine("dev-overview")
        _serve(eng, n=3)
        router = ClusterRouter(LoadBalancer(), config=ClusterConfig(),
                               enable_metrics=False)
        router.register_engine(eng)
        out = router.overview()
        assert out["aggregate"]["endpoints"] == 1
        assert out["aggregate"]["reporting"] == 1
        rep = out["replicas"][0]
        assert rep["device"]["steps"]["count"] > 0
        assert rep["engine"]["tokens_generated"] > 0

    def test_unreachable_remote_degrades_per_replica(self):
        from llmq_tpu.cluster.router import ClusterRouter
        from llmq_tpu.core.config import ClusterConfig
        from llmq_tpu.loadbalancer.load_balancer import LoadBalancer
        router = ClusterRouter(LoadBalancer(), config=ClusterConfig(),
                               enable_metrics=False)
        router.register_remote("http://127.0.0.1:1",   # nothing listens
                               endpoint_id="gone")
        out = router.overview()
        assert out["aggregate"]["reporting"] == 0
        assert "error" in out["replicas"][0]


# -- overhead guard (acceptance: instrumentation < 3 % of an echo step) --------

class TestOverheadGuard:
    def test_note_step_under_3pct_of_echo_request(self):
        """Deterministic decomposition, mirroring the PR-3 trace-plane
        guard: measure one echo request end-to-end through the engine,
        then the per-call cost of the full per-chunk instrumentation
        (3 perf_counter reads + note_step), and require
        chunks-per-request × per-call < 3 % of the request."""
        eng = _echo_engine("dev-overhead", chunk=1)
        n, max_new = 24, 16
        t0 = time.perf_counter()
        _serve(eng, n=n, max_new=max_new)
        per_request = (time.perf_counter() - t0) / n
        # Actual instrumented chunks per request (decode steps batch
        # across slots, so this is far below max_new).
        calls_per_request = eng.get_stats()["device"]["steps"]["count"] / n

        tel = DeviceTelemetry("dev-overhead-probe", metrics=True)
        # MIN over several batches: the guard measures the code's
        # cost, not the CI box's scheduler noise — a single batch
        # inflated by a contended core flaked this test once already.
        per_call = float("inf")
        for _ in range(5):
            m = 2000
            t0 = time.perf_counter()
            for _ in range(m):
                a = time.perf_counter()
                b = time.perf_counter()
                c = time.perf_counter()
                tel.note_step(b - a, c - b, 0.0, 1)
            per_call = min(per_call, (time.perf_counter() - t0) / m)
        cost = calls_per_request * per_call
        assert cost < 0.03 * per_request, (
            f"instrumentation {cost * 1e6:.1f}µs/request "
            f"({calls_per_request:.1f} chunks × {per_call * 1e6:.1f}µs) "
            f"vs request {per_request * 1e6:.1f}µs")
