"""Prefill/decode disaggregation plane (llmq_tpu/disagg/,
docs/disaggregation.md): the store tier as a cluster-wide KV exchange.

- KVExchange unit semantics: publish/claim payload fidelity, claim-is-
  consume, TTL expiry, torn-blob degradation, per-role counters;
- the hard off-switch: ``disagg.enabled=false`` builds nothing and
  routing/engine behavior is byte-identical to the unified plane;
- role-aware routing over the REAL product path (roles advertised via
  /health, learned from probes): long first turns → prefill replicas,
  follow-ups → decode, the prefill→decode affinity handoff, and the
  never-fail guarantee when only wrong-role replicas remain;
- plane-level cross-replica exchange: payload round-trip bit-exact
  through two planes sharing one store, miss negative-caching, foreign
  page-spec refusal (recompute, never inject);
- conversation-level handoff on echo engines: prefill publishes each
  finished turn, decode claims it with a store-tier hit and ZERO
  recompute; expired claims fall back to history-text recompute with
  identical output; drain-time warm migration;
- replica restart rehydration: owned store blobs re-adopted, prefix
  handles re-registered, re-arrivals hit the store tier;
- metric families + scrape-time flush;
- role-aware control-plane scaling (under-represented side wins).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from llmq_tpu.api.server import ApiServer
from llmq_tpu.cluster import build_cluster_router
from llmq_tpu.conversation.persistence import InMemoryStore
from llmq_tpu.conversation.state_manager import StateManager
from llmq_tpu.core.config import (ClusterConfig, ConversationConfig,
                                  DisaggConfig, KVTieringConfig,
                                  LoadBalancerConfig, PrefixCacheConfig,
                                  default_config)
from llmq_tpu.core.types import Message
from llmq_tpu.disagg import (DisaggCoordinator, KVExchange, build_disagg,
                             flush_metrics)
from llmq_tpu.engine.engine import GenRequest, InferenceEngine
from llmq_tpu.engine.executor import EchoExecutor
from llmq_tpu.engine.tokenizer import ByteTokenizer
from llmq_tpu.loadbalancer import LoadBalancer
from llmq_tpu.observability.usage import get_usage_ledger
from llmq_tpu.tiering import KVTieringPlane


@pytest.fixture(autouse=True)
def _usage_off():
    led = get_usage_ledger()
    led.reconfigure(enabled=False)
    led.clear()
    yield
    led.reconfigure(enabled=False)
    led.clear()


def wait_until(fn, timeout=5.0, step=0.002):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


class FakeKVExec:
    """Numpy 'device' with a deterministic per-page payload (same shape
    as the tiering tests') so exchange fidelity is assertable."""

    def __init__(self):
        self.injected = {}

    def kv_page_spec(self):
        return [((2, 4, 8), np.dtype(np.float32))]

    def export_kv_pages(self, pages):
        out = np.stack(
            [np.full((2, 4, 8), float(p), np.float32) for p in pages],
            axis=1)
        return [out]

    def import_kv_pages(self, pages, leaves):
        for i, p in enumerate(pages):
            self.injected[p] = np.asarray(leaves[0][:, i]).copy()


def mk_plane(name="planeA", store=None, cfg=None):
    plane = KVTieringPlane(cfg or KVTieringConfig(enabled=True), name,
                           FakeKVExec())
    plane.store = store if store is not None else InMemoryStore()
    return plane


def _bufs(n, seed=7):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 255, 256, np.uint8).astype(np.uint8)
            for _ in range(n)]


SPECS = [((2, 4, 8), np.dtype(np.float32))]


class FakeNow:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


# -- exchange unit semantics ---------------------------------------------------


class TestKVExchange:
    def test_publish_claim_roundtrip_bit_identical(self):
        store = InMemoryStore()
        pub = KVExchange(store, role="prefill", metrics=False)
        sub = KVExchange(store, role="decode", metrics=False)
        bufs = _bufs(3)
        meta = {"conv_id": "c", "tokens": [1, 2, 3], "length": 3,
                "pending": None, "n_pages": 3, "owner": "prefill0"}
        pub.publish("c", bufs, SPECS, meta)
        got = sub.claim("c")
        assert got is not None
        gbufs, gspecs, gmeta = got
        assert len(gbufs) == 3
        for a, b in zip(gbufs, bufs):
            assert np.array_equal(np.asarray(a)[:256], b)
        assert [tuple(s[0]) for s in gspecs] == [SPECS[0][0]]
        assert gmeta["tokens"] == [1, 2, 3]
        assert gmeta["role"] == "prefill"      # publisher stamped
        assert "published_at" in gmeta
        assert pub.totals["published"] == 1
        assert sub.totals["claimed"] == 1

    def test_claim_is_consume(self):
        store = InMemoryStore()
        x = KVExchange(store, metrics=False)
        x.publish("c", _bufs(1), SPECS, {"conv_id": "c"})
        assert x.claim("c") is not None
        assert x.claim("c") is None            # consumed
        assert KVExchange.key_for("c") not in store.list_kv()

    def test_ttl_expiry_counts_publisher_role(self):
        now = FakeNow()
        store = InMemoryStore()
        pub = KVExchange(store, role="prefill", claim_ttl_s=10.0,
                         metrics=False, now_fn=now)
        sub = KVExchange(store, role="decode", claim_ttl_s=10.0,
                         metrics=False, now_fn=now)
        pub.publish("c", _bufs(1), SPECS, {"conv_id": "c"})
        now.t += 11.0
        assert sub.claim("c") is None
        assert sub.totals["expired"] == 1
        # Expired entry was deleted, not left to rot.
        assert KVExchange.key_for("c") not in store.list_kv()

    def test_torn_blob_counts_fallback(self):
        store = InMemoryStore()
        x = KVExchange(store, role="decode", metrics=False)
        x.publish("c", _bufs(2), SPECS, {"conv_id": "c"})
        blob = store.load_kv(KVExchange.key_for("c"))
        store.save_kv(KVExchange.key_for("c"), blob[:-20])  # torn
        assert x.claim("c") is None
        assert x.totals["fallback"] == 1

    def test_pending_and_stats(self):
        store = InMemoryStore()
        x = KVExchange(store, role="prefill", metrics=False)
        x.publish("a", _bufs(1), SPECS, {"conv_id": "a"})
        x.publish("b", _bufs(1), SPECS, {"conv_id": "b"})
        assert x.pending() == ["a", "b"]
        st = x.stats()
        assert st["role"] == "prefill" and st["published"] == 2


# -- hard off-switch -----------------------------------------------------------


def mk_echo_engine(name="disagg0", tiering=None, metrics=False):
    tok = ByteTokenizer()
    ex = EchoExecutor(batch_size=4, page_size=8, num_pages=128,
                      max_pages_per_seq=16, eos_id=tok.eos_id,
                      chunk_size=4)
    return InferenceEngine(ex, tok, enable_metrics=metrics, name=name,
                           kv_pin_ttl=600.0, kv_tiering=tiering,
                           prefix_cache=PrefixCacheConfig(enabled=True))


def run_turn(eng, rid, prompt, conv, history="", tokens=8):
    h = eng.submit(GenRequest(id=rid, prompt=prompt,
                              conversation_id=conv,
                              history_text=history,
                              max_new_tokens=tokens))
    eng.run_until_idle()
    assert h.result is not None and h.result.finish_reason in (
        "eos", "length")
    return h


class TestOffSwitch:
    def test_default_config_disabled(self):
        cfg = default_config()
        assert cfg.disagg.enabled is False
        assert cfg.disagg.role == "unified"

    def test_build_disagg_none_and_engine_hooks_inert(self):
        cfg = default_config()
        eng = mk_echo_engine(tiering=KVTieringConfig(enabled=True))
        assert build_disagg(cfg, eng, InMemoryStore()) is None
        assert eng.disagg_role == "unified"
        assert eng.on_conversation_cached is None
        assert eng._tiering.exchange is None
        # Serving is the plain unified path.
        h = run_turn(eng, "t1", "hello off-switch", "c")
        assert h.result.text
        eng.stop()

    def test_invalid_role_rejected(self):
        with pytest.raises(ValueError):
            DisaggConfig(role="speculate")

    def test_health_omits_role_when_unified(self):
        eng = mk_echo_engine()
        api = ApiServer(default_config(), engine=eng)
        try:
            assert "role" not in api.health_check(None)[1]
            eng.disagg_role = "prefill"
            assert api.health_check(None)[1]["role"] == "prefill"
        finally:
            eng.stop()

    def test_router_routes_identically_without_disagg(self):
        """With disagg unset the role helpers are inert: no exclusions,
        no disagg stats block, round-robin order unchanged."""
        from llmq_tpu.cluster.router import ClusterRouter
        eng_a, eng_b = mk_echo_engine("ra"), mk_echo_engine("rb")
        lb = LoadBalancer(LoadBalancerConfig(strategy="round_robin",
                                             health_check_interval=0.0))
        eng_a.start()
        eng_b.start()
        router = ClusterRouter(lb, config=ClusterConfig(),
                               enable_metrics=False)
        router.register_engine(eng_a, endpoint_id="ra")
        router.register_engine(eng_b, endpoint_id="rb")
        assert router.disagg is None
        assert router._role_pref(
            Message(id="m", content="x" * 4096, user_id="u"), None) is None
        seen = []
        for i in range(4):
            m = Message(id=f"m{i}", content="x" * 4096, user_id="u",
                        timeout=30.0)
            router.process_fn(None, m)
            seen.append(m.metadata["endpoint_id"])
        assert set(seen) == {"ra", "rb"}      # plain round-robin spread
        assert "disagg" not in router.get_stats()
        eng_a.stop()
        eng_b.stop()


# -- role-aware routing (product path: roles learned from /health) -------------


def _serve_roled(roles):
    """One echo replica per role, each behind its own REST server with
    the role advertised on /health — the only control channel."""
    engines, servers, urls = [], [], []
    for i, role in enumerate(roles):
        eng = mk_echo_engine(f"replica{i}")
        eng.start()
        eng.disagg_role = role
        api = ApiServer(default_config(), engine=eng)
        port = api.start(host="127.0.0.1", port=0)
        engines.append(eng)
        servers.append(api)
        urls.append(f"http://127.0.0.1:{port}")
    return engines, servers, urls


def _disagg_router(urls, *, state_manager=None, long_prompt_tokens=32,
                   **ccfg):
    lb = LoadBalancer(LoadBalancerConfig(strategy="round_robin",
                                         health_check_interval=0.0))
    cfg = default_config()
    cfg.cluster = ClusterConfig(peers=list(urls), **ccfg)
    cfg.disagg = DisaggConfig(enabled=True,
                              long_prompt_tokens=long_prompt_tokens)
    cfg.queue.enable_metrics = False
    router = build_cluster_router(cfg, lb, state_manager=state_manager)
    lb.check_health_once()                    # probes learn the roles
    return router


class TestRoleRouting:
    def test_roles_learned_from_health_probes(self):
        engines, servers, urls = _serve_roled(["prefill", "decode"])
        try:
            router = _disagg_router(urls)
            roles = {router._role_of(e): e.id
                     for e in router.lb.endpoints()}
            assert set(roles) == {"prefill", "decode"}
        finally:
            for s in servers:
                s.stop()
            for e in engines:
                e.stop()

    def test_long_first_turn_to_prefill_short_to_decode(self):
        engines, servers, urls = _serve_roled(["prefill", "decode"])
        try:
            router = _disagg_router(urls, long_prompt_tokens=32)
            by_role = {router._role_of(e): e.id
                       for e in router.lb.endpoints()}
            long_turn = Message(id="m1", content="x" * 200,  # ≥32 tok
                                user_id="u", timeout=30.0)
            router.process_fn(None, long_turn)
            assert long_turn.metadata["endpoint_id"] == by_role["prefill"]
            short = Message(id="m2", content="hi", user_id="u",
                            timeout=30.0)
            router.process_fn(None, short)
            assert short.metadata["endpoint_id"] == by_role["decode"]
            assert router.get_stats()["disagg"]["role_routes"] >= 2
        finally:
            for s in servers:
                s.stop()
            for e in engines:
                e.stop()

    def test_followup_handoff_leaves_prefill_affinity(self):
        """A conversation born on the prefill replica must NOT return
        there on turn 2 — the router deliberately breaks affinity
        (reason "handoff") and the exchange carries the KV across."""
        engines, servers, urls = _serve_roled(["prefill", "decode"])
        try:
            sm = StateManager(ConversationConfig(cleanup_interval=0))
            sm.get_or_create("conv-h", "u")
            router = _disagg_router(urls, state_manager=sm,
                                    long_prompt_tokens=32)
            by_role = {router._role_of(e): e.id
                       for e in router.lb.endpoints()}
            t1 = Message(id="t1", content="y" * 200, user_id="u",
                         conversation_id="conv-h", timeout=30.0)
            router.process_fn(None, t1)
            assert t1.metadata["endpoint_id"] == by_role["prefill"]
            t2 = Message(id="t2", content="followup", user_id="u",
                         conversation_id="conv-h", timeout=30.0,
                         metadata={"history_text": "y" * 200})
            router.process_fn(None, t2)
            assert t2.metadata["endpoint_id"] == by_role["decode"]
            st = router.get_stats()["disagg"]
            assert st["handoffs"] == 1
        finally:
            for s in servers:
                s.stop()
            for e in engines:
                e.stop()

    def test_wrong_role_only_cluster_still_dispatches(self):
        """Steering must never fail a dispatch unified routing would
        serve: decode-preferred turns on an all-prefill cluster."""
        engines, servers, urls = _serve_roled(["prefill", "prefill"])
        try:
            router = _disagg_router(urls)
            m = Message(id="m1", content="hi", user_id="u",
                        timeout=30.0)   # short → decode preference
            router.process_fn(None, m)
            assert m.metadata.get("endpoint_id")
        finally:
            for s in servers:
                s.stop()
            for e in engines:
                e.stop()

    def test_unified_endpoints_serve_any_preference(self):
        engines, servers, urls = _serve_roled(["unified"])
        try:
            router = _disagg_router(urls)
            for i, content in enumerate(("z" * 200, "hi")):
                m = Message(id=f"m{i}", content=content, user_id="u",
                            timeout=30.0)
                router.process_fn(None, m)
                assert m.metadata.get("endpoint_id")
        finally:
            for s in servers:
                s.stop()
            for e in engines:
                e.stop()


# -- plane-level exchange (payload fidelity across planes) ---------------------


class TestPlaneExchange:
    def test_cross_plane_payload_roundtrip(self):
        store = InMemoryStore()
        a = mk_plane("prefillA", store)
        b = mk_plane("decodeB", store)
        a.exchange = KVExchange(store, role="prefill", metrics=False)
        b.exchange = KVExchange(store, role="decode", metrics=False)
        try:
            a.demote("c", [3, 5], list(range(16)), 16, None)
            assert a.flush_jobs()
            assert a.export_to_exchange("c")
            assert a.flush_jobs()
            assert b.prepare("c", remote=True)
            status, entry = None, None

            def claimed():
                nonlocal status, entry
                status, entry = b.claim("c")
                return status == "ready"

            assert wait_until(claimed)
            assert entry.tokens == list(range(16))
            leaves = b.unpack(entry)
            assert np.all(np.asarray(leaves[0][:, 0]) == 3.0)
            assert np.all(np.asarray(leaves[0][:, 1]) == 5.0)
            assert entry.source_tier == "store"
            b.release(entry)
        finally:
            a.stop()
            b.stop()

    def test_exchange_miss_degrades_and_negative_caches(self):
        store = InMemoryStore()
        b = mk_plane("decodeB", store)
        b.exchange = KVExchange(store, role="decode", metrics=False,
                                miss_ttl_s=60.0)
        try:
            assert b.prepare("ghost", remote=True)
            assert wait_until(lambda: b.claim("ghost")[0] == "none")
            # Negative cache: the next remote prepare declines without
            # creating a placeholder.
            assert b.prepare("ghost", remote=True) is False
        finally:
            b.stop()

    def test_local_prepare_never_touches_exchange(self):
        store = InMemoryStore()
        b = mk_plane("decodeB", store)
        b.exchange = KVExchange(store, metrics=False)
        try:
            assert b.prepare("nothing-local", remote=False) is False
            assert b.claim("nothing-local") == ("none", None)
        finally:
            b.stop()

    def test_foreign_spec_refused_tokens_survive(self):
        """A heterogeneous peer's page bytes are never injected: the
        claimer keeps the token stream and recomputes."""
        store = InMemoryStore()
        b = mk_plane("decodeB", store)
        b.exchange = KVExchange(store, role="decode", metrics=False)
        pub = KVExchange(store, role="prefill", metrics=False)
        foreign = [((4, 8, 16), np.dtype(np.int8))]
        fbufs = [np.zeros(4 * 8 * 16, np.uint8) for _ in range(2)]
        pub.publish("c", fbufs, foreign,
                    {"conv_id": "c", "tokens": [9, 8, 7], "length": 3,
                     "n_pages": 2})
        try:
            assert b.prepare("c", remote=True)
            status, entry = None, None

            def claimed():
                nonlocal status, entry
                status, entry = b.claim("c")
                return status == "ready"

            assert wait_until(claimed)
            assert entry.payload is None
            assert entry.tier == "recompute"
            assert entry.tokens == [9, 8, 7]
            b.release(entry)
        finally:
            b.stop()


# -- conversation-level handoff (echo engines, full promote path) --------------


def mk_disagg_engine(name, role, store, *, claim_ttl=120.0, now_fn=None,
                     metrics=False):
    eng = mk_echo_engine(name, tiering=KVTieringConfig(enabled=True),
                         metrics=metrics)
    sm = StateManager(ConversationConfig(cleanup_interval=0),
                      store=store)
    eng.attach_conversation_manager(sm)
    xchg = KVExchange(store, role=role, claim_ttl_s=claim_ttl,
                      metrics=metrics, now_fn=now_fn)
    coord = DisaggCoordinator(
        DisaggConfig(enabled=True, role=role, claim_ttl_s=claim_ttl),
        eng, xchg)
    return eng, sm, coord


class TestConversationHandoff:
    def test_prefill_publishes_decode_claims_zero_recompute(self):
        store = InMemoryStore()
        peng, psm, pcoord = mk_disagg_engine("prefill0", "prefill",
                                             store)
        deng, dsm, dcoord = mk_disagg_engine("decode0", "decode", store)
        try:
            psm.get_or_create("c", "u")
            h1 = run_turn(peng, "t1", "the quick brown fox", "c")
            # The finished turn's KV reaches the exchange (engine hook
            # → demote → FIFO publish on the plane worker).
            assert wait_until(
                lambda: KVExchange.key_for("c") in store.list_kv())
            # Follow-up lands on the DECODE replica which never served
            # this conversation: remote prepare claims the exchange.
            h2 = run_turn(deng, "t2", " jumps over", "c",
                          history="the quick brown fox")
            assert h2.result.kv_tier == "store"   # exchange hit
            assert h2.result.cached_tokens > 0
            st = deng.get_stats()["kv_tiering"]
            assert st["hits"].get("recompute", 0) == 0
            assert pcoord.exchange.totals["published"] == 1
            assert dcoord.exchange.totals["claimed"] == 1
            assert h1.result.text and h2.result.text
        finally:
            peng.stop()
            deng.stop()
            psm.stop()
            dsm.stop()

    def test_expired_claim_falls_back_to_recompute_identically(self):
        """A dead prefill replica's publication ages out: the decode
        side recomputes from history text — same tokens as a fresh
        unified engine, never garbage KV, never a hang."""
        base = mk_echo_engine("base0",
                              tiering=KVTieringConfig(enabled=True))
        want = run_turn(base, "tb", " jumps over", "c",
                        history="the quick brown fox").result.tokens
        base.stop()

        now = FakeNow()
        store = InMemoryStore()
        peng, psm, _ = mk_disagg_engine("prefill0", "prefill", store,
                                        claim_ttl=10.0, now_fn=now)
        deng, dsm, dcoord = mk_disagg_engine("decode0", "decode", store,
                                             claim_ttl=10.0, now_fn=now)
        try:
            psm.get_or_create("c", "u")
            run_turn(peng, "t1", "the quick brown fox", "c")
            assert wait_until(
                lambda: KVExchange.key_for("c") in store.list_kv())
            now.t += 11.0                      # publication expires
            h2 = run_turn(deng, "t2", " jumps over", "c",
                          history="the quick brown fox")
            assert h2.result.tokens == want    # recompute, bit-equal
            assert dcoord.exchange.totals["expired"] == 1
        finally:
            peng.stop()
            deng.stop()
            psm.stop()
            dsm.stop()

    def test_drain_publish_warm_migrates_conversations(self):
        """Drain-time migration: ANY role's warm conversations go to
        the exchange; a peer resumes them with a store hit."""
        store = InMemoryStore()
        aeng, asm, acoord = mk_disagg_engine("unified0", "unified",
                                             store)
        beng, bsm, _ = mk_disagg_engine("decode0", "decode", store)
        try:
            asm.get_or_create("warm", "u")
            run_turn(aeng, "t1", "conversation to migrate", "warm")
            # Unified role: nothing published on finish...
            assert KVExchange.key_for("warm") not in store.list_kv()
            # ...until the drain migration pushes the warm set.
            assert acoord.publish_warm() == 1
            assert acoord.plane.flush_jobs()
            assert KVExchange.key_for("warm") in store.list_kv()
            h2 = run_turn(beng, "t2", " resumed elsewhere", "warm",
                          history="conversation to migrate")
            assert h2.result.kv_tier == "store"
            st = beng.get_stats()["kv_tiering"]
            assert st["hits"].get("recompute", 0) == 0
        finally:
            aeng.stop()
            beng.stop()
            asm.stop()
            bsm.stop()


# -- replica restart rehydration -----------------------------------------------


class TestRehydration:
    def test_plane_rehydrate_owned_blobs_only(self):
        store = InMemoryStore()
        # host_capacity_mb=0: demotes spill straight to the store.
        a = mk_plane("replica0", store,
                     KVTieringConfig(enabled=True, host_capacity_mb=0))
        a.demote("mine", [2, 4], list(range(16)), 16, None)
        assert wait_until(lambda: a.counts().get("store", 0) == 1)
        a.stop()
        # A blob some OTHER replica owns, plus an exchange entry:
        # neither may be adopted.
        b = mk_plane("replica1", store,
                     KVTieringConfig(enabled=True, host_capacity_mb=0))
        b.demote("theirs", [6], list(range(8)), 8, None)
        assert wait_until(lambda: b.counts().get("store", 0) == 1)
        b.stop()
        KVExchange(store, metrics=False).publish(
            "xc", _bufs(1), SPECS, {"conv_id": "xc"})

        restarted = mk_plane("replica0", store,
                             KVTieringConfig(enabled=True,
                                             host_capacity_mb=0))
        try:
            adopted = restarted.rehydrate(owner="replica0")
            assert [cid for cid, _ in adopted] == ["mine"]
            status, entry = restarted.claim("mine")
            assert status == "ready" and entry.source_tier == "store"
            leaves = restarted.unpack(entry)
            assert np.all(np.asarray(leaves[0][:, 0]) == 2.0)
            restarted.release(entry)
        finally:
            restarted.stop()

    def test_rehydrate_registers_prefix_handles(self):
        """Engine-level restart: rehydrate_tiered_conversations adopts
        the blob AND re-registers the prefix handle (tier "store") on
        a conversation faulted back from the same store."""
        store = InMemoryStore()
        a = mk_plane("restart0", store,
                     KVTieringConfig(enabled=True, host_capacity_mb=0))
        a.demote("c", [3], list(range(8)), 8, None)
        assert wait_until(lambda: a.counts().get("store", 0) == 1)
        a.stop()

        eng = mk_echo_engine("restart0",
                             tiering=KVTieringConfig(enabled=True))
        sm = StateManager(ConversationConfig(cleanup_interval=0),
                          store=store)
        sm.get_or_create("c", "u")             # durable conversation
        sm.stop()
        # "Restart": fresh engine + state manager over the same store.
        eng._tiering.stop()
        eng._tiering = a.__class__(
            KVTieringConfig(enabled=True, host_capacity_mb=0),
            "restart0", FakeKVExec())
        sm2 = StateManager(ConversationConfig(cleanup_interval=0),
                           store=store)
        eng.attach_conversation_manager(sm2)
        try:
            assert eng.rehydrate_tiered_conversations() == 1
            h = sm2.prefix_handle("c")
            assert h is not None and h["tier"] == "store"
            assert h["length"] == 8 and h["pages"] == 1
            cached, _ = eng.prefill_estimate("c", 4)
            assert cached > 0                  # promotable again
        finally:
            eng.stop()
            sm2.stop()

    def test_build_disagg_rehydrates_on_start(self):
        store = InMemoryStore()
        a = mk_plane("boot0", store,
                     KVTieringConfig(enabled=True, host_capacity_mb=0))
        a.demote("c", [5], list(range(8)), 8, None)
        assert wait_until(lambda: a.counts().get("store", 0) == 1)
        a.stop()

        cfg = default_config()
        cfg.disagg = DisaggConfig(enabled=True, role="decode")
        eng = mk_echo_engine("boot0",
                             tiering=KVTieringConfig(enabled=True))
        eng._tiering.stop()
        eng._tiering = a.__class__(
            KVTieringConfig(enabled=True, host_capacity_mb=0), "boot0",
            FakeKVExec())
        sm = StateManager(ConversationConfig(cleanup_interval=0),
                          store=store)
        sm.get_or_create("c", "u")
        eng.attach_conversation_manager(sm)
        try:
            coord = build_disagg(cfg, eng, store)
            assert coord is not None
            assert eng._tiering.counts().get("store", 0) == 1
            assert eng.disagg_role == "decode"
        finally:
            eng.stop()
            sm.stop()


# -- metrics -------------------------------------------------------------------


class TestDisaggMetrics:
    def test_label_contract_covers_disagg(self):
        from llmq_tpu.metrics.registry import LABEL_CONTRACT
        assert LABEL_CONTRACT["role"] == frozenset(
            {"prefill", "decode", "unified"})
        assert "handoff" in LABEL_CONTRACT["reason"]

    def test_exchange_families_flushed_at_scrape(self):
        from llmq_tpu.metrics.registry import exposition

        store = InMemoryStore()
        now = FakeNow()
        pub = KVExchange(store, role="prefill", claim_ttl_s=10.0,
                         metrics=True, now_fn=now)
        sub = KVExchange(store, role="decode", claim_ttl_s=10.0,
                         metrics=True, now_fn=now)
        pub.publish("a", _bufs(1), SPECS, {"conv_id": "a"})
        assert sub.claim("a") is not None
        pub.publish("b", _bufs(1), SPECS, {"conv_id": "b"})
        now.t += 11.0
        assert sub.claim("b") is None          # expired
        sub.note_fallback()
        exp = exposition().decode()            # scrape-time flush
        assert ('llm_queue_kv_exchange_published_total'
                '{role="prefill"} 2') in exp
        assert ('llm_queue_kv_exchange_claimed_total'
                '{role="decode"} 1') in exp
        assert ('llm_queue_kv_exchange_expired_total'
                '{role="prefill"} 1') in exp   # publisher's role
        assert ('llm_queue_kv_exchange_fallback_total'
                '{role="decode"} 1') in exp
        assert ('llm_queue_kv_handoff_ms_count'
                '{role="decode"} 1') in exp
        # Buffered counters drained; lifetime totals survive.
        flush_metrics()
        assert sub.totals["claimed"] == 1


# -- role-aware control-plane scaling ------------------------------------------


class TestRoleAwareScaling:
    def test_new_replica_joins_underrepresented_side(self):
        from llmq_tpu.cluster.router import ClusterRouter
        from llmq_tpu.controlplane import (LocalEnginePool,
                                           ReplicaController)
        from llmq_tpu.core.config import ControlPlaneConfig

        engines = []

        def factory(seq):
            eng = mk_echo_engine(f"pool{seq}")
            eng.start()
            engines.append(eng)
            return eng

        lb = LoadBalancer(LoadBalancerConfig(
            strategy="round_robin", health_check_interval=0.0))
        router = ClusterRouter(lb, config=ClusterConfig(),
                               enable_metrics=False)
        pool = LocalEnginePool(factory, supervise=False)
        ctl = ReplicaController(
            config=ControlPlaneConfig(enabled=True, interval=0),
            router=router, pool=pool, enable_metrics=False)
        ctl.disagg = DisaggConfig(enabled=True)
        try:
            # Empty set → decode first (ties go to decode)...
            assert ctl._role_for_new_replica() == "decode"
            assert ctl._provision_one()
            ep0 = router.lb.endpoints()[0]
            assert router._role_of(ep0) == "decode"
            # ...then the under-represented prefill side.
            assert ctl._role_for_new_replica() == "prefill"
            assert ctl._provision_one()
            roles = sorted(router._role_of(e)
                           for e in router.lb.endpoints())
            assert roles == ["decode", "prefill"]
            # Disagg off → no role hint, no pinning.
            ctl.disagg = None
            assert ctl._role_for_new_replica() is None
            assert ctl._provision_one()
            assert pool.role_hint is None
        finally:
            pool.stop()
            for e in engines:
                if e.running:
                    e.stop()


# -- SIGKILL mid-handoff chaos (real OS processes, InvariantChecker) -----------


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_health(url: str, timeout: float = 60.0) -> None:
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(f"{url}/health", timeout=2) as r:
                if r.status == 200:
                    return
        except OSError as e:
            last = e
        time.sleep(0.1)
    raise TimeoutError(f"{url} never became healthy: {last}")


def _post(url: str, path: str, body: dict) -> dict:
    req = urllib.request.Request(
        f"{url}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def _get(url: str, path: str) -> dict:
    with urllib.request.urlopen(f"{url}{path}", timeout=10) as r:
        return json.loads(r.read())


def _scrape(url: str) -> str:
    with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
        return r.read().decode()


def _counter(text: str, family: str) -> float:
    total = 0.0
    for ln in text.splitlines():
        if ln.startswith(family) and " " in ln:
            total += float(ln.rsplit(" ", 1)[1])
    return total


def _spawn_replica(port: int, env: dict, role: str) -> subprocess.Popen:
    e = dict(env)
    e["LLMQ_DISAGG_ROLE"] = role
    return subprocess.Popen(
        [sys.executable, "-m", "llmq_tpu", "--backend", "echo",
         "--host", "127.0.0.1", "--port", str(port), "serve"],
        cwd=REPO, env=e, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)


def test_prefill_sigkill_mid_handoff_zero_loss_zero_dup(tmp_path):
    """The disagg acceptance chaos path over REAL OS processes: one
    prefill + one decode replica sharing a sqlite KV store, a gateway
    routing by role. Long first turns land on the prefill replica,
    which publishes each finished turn's KV to the exchange; the
    prefill replica is SIGKILLed mid-flood; every in-flight and
    follow-up message still reaches exactly one completion
    (InvariantChecker: zero loss, zero duplicates), follow-ups claim
    the dead replica's published KV from the exchange, and the
    gateway's stats expose the learned role map."""
    from llmq_tpu.chaos.invariants import InvariantChecker

    env = dict(os.environ)
    env["LLMQ_QUEUE_ENABLE_METRICS"] = "true"
    env["LLMQ_LOADBALANCER_STRATEGY"] = "round_robin"
    env["LLMQ_LOADBALANCER_HEALTH_CHECK_INTERVAL"] = "0.5"
    env["LLMQ_QUEUE_WORKER_PROCESS_INTERVAL"] = "0.01"
    env["LLMQ_DISAGG_ENABLED"] = "true"
    env["LLMQ_DISAGG_LONG_PROMPT_TOKENS"] = "32"
    env["LLMQ_EXECUTOR_KV_TIERING_ENABLED"] = "true"
    env["LLMQ_PERSISTENCE_BACKEND"] = "sqlite"
    env["LLMQ_PERSISTENCE_SQLITE_PATH"] = str(tmp_path / "shared.db")

    ports = [_free_port(), _free_port()]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    prefill = _spawn_replica(ports[0], env, "prefill")
    decode = _spawn_replica(ports[1], env, "decode")
    gw_port = _free_port()
    gw = f"http://127.0.0.1:{gw_port}"
    procs = [prefill, decode]
    ck = InvariantChecker()
    try:
        for u in urls:
            _wait_health(u)
        assert _get(urls[0], "/health")["role"] == "prefill"
        assert _get(urls[1], "/health")["role"] == "decode"
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "llmq_tpu", "--host", "127.0.0.1",
             "--port", str(gw_port),
             "--peers", f"{urls[0]},{urls[1]}", "gateway"],
            cwd=REPO, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL))
        _wait_health(gw)

        # Role routing engages once the gateway's health probes have
        # carried the replicas' role advertisements home (the 0.5 s
        # loop) — wait for the learned map before flooding.
        def roles_learned():
            st = _get(gw, "/api/v1/cluster/stats")
            r = (st.get("disagg") or {}).get("roles") or {}
            return set(r.values()) == {"prefill", "decode"}

        assert wait_until(roles_learned, timeout=20.0, step=0.2), \
            _get(gw, "/api/v1/cluster/stats")

        def drain_all(mids, deadline_s=60.0):
            deadline = time.time() + deadline_s
            left = set(mids)
            while left and time.time() < deadline:
                for mid in list(left):
                    m = _get(gw, f"/api/v1/messages/{mid}")
                    if m["status"] == "completed" and m["response"]:
                        ck.completed(mid)
                        left.discard(mid)
                    elif m["status"] == "failed":
                        ck.failed(mid)
                        left.discard(mid)
                if left:
                    time.sleep(0.05)
            return left

        # Phase 1: long first turns. Role routing steers every one of
        # them to the prefill replica, which publishes the finished
        # KV to the exchange as each completes.
        convs, turn1 = [], []
        for i in range(6):
            conv = _post(gw, "/api/v1/conversations",
                         {"user_id": "t"})["conversation_id"]
            convs.append(conv)
            mid = _post(gw, f"/api/v1/conversations/{conv}/messages",
                        {"content": f"long prompt {i} " + "x" * 220,
                         "user_id": "t"})["message_id"]
            ck.submitted(mid)
            turn1.append(mid)
        assert drain_all(turn1) == set()
        by_ep = {}
        for mid in turn1:
            ep = _get(gw, f"/api/v1/messages/{mid}"
                      )["metadata"]["endpoint_id"]
            by_ep[ep] = by_ep.get(ep, 0) + 1
        roles = _get(gw, "/api/v1/cluster/stats")["disagg"]["roles"]
        prefill_ep = next(e for e, r in roles.items() if r == "prefill")
        assert by_ep == {prefill_ep: 6}        # role routing held
        # The prefill side published its finished turns.
        pre_metrics = _scrape(urls[0])
        assert _counter(
            pre_metrics,
            'llm_queue_kv_exchange_published_total{role="prefill"}') >= 6

        # Phase 2: SIGKILL the prefill replica MID-FLOOD — a second
        # wave of long first turns is in flight when it dies.
        wave2 = []
        for i in range(4):
            mid = _post(gw, "/api/v1/messages",
                        {"content": f"wave2 {i} " + "y" * 220,
                         "user_id": "t"})["message_id"]
            ck.submitted(mid)
            wave2.append(mid)
        prefill.send_signal(signal.SIGKILL)
        prefill.wait(timeout=10)

        # Phase 3: follow-up turns for every conversation born on the
        # now-dead replica. The decode replica claims the published KV
        # from the exchange (the promote path IS the receive path);
        # where the handoff cannot be served, history-text recompute
        # answers — never a hang, never garbage KV.
        turn2 = []
        for conv in convs:
            mid = _post(gw, f"/api/v1/conversations/{conv}/messages",
                        {"content": "follow-up", "user_id": "t"}
                        )["message_id"]
            ck.submitted(mid)
            turn2.append(mid)
        assert drain_all(wave2) == set()
        assert drain_all(turn2) == set()
        ck.check()                              # zero loss, zero dup
        for mid in turn2:
            ep = _get(gw, f"/api/v1/messages/{mid}"
                      )["metadata"]["endpoint_id"]
            assert ep != prefill_ep             # dead replica avoided
        dec_metrics = _scrape(urls[1])
        claimed = _counter(
            dec_metrics,
            'llm_queue_kv_exchange_claimed_total{role="decode"}')
        assert claimed >= 1                     # real cross-process
        assert 'llm_queue_kv_handoff_ms_count' in dec_metrics
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
