"""Multi-process distributed backend (SURVEY §2.2 "Distributed
communication backend"): two OS processes rendezvous through
``distributed_init`` (the DCN coordination analogue of NCCL/MPI
bootstrap) and run a cross-process collective on the CPU backend —
the same code path a multi-host v5e-16 deployment uses (BASELINE
config #5), minus the ICI.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
import numpy as np

sys.path.insert(0, {repo!r})
import jax
from llmq_tpu.parallel.mesh import distributed_init, make_mesh

distributed_init(coordinator={coord!r}, num_processes=2,
                 process_id={pid}, initialization_timeout=60)
# Idempotency: a second call must be a clean no-op.
distributed_init(coordinator={coord!r}, num_processes=2,
                 process_id={pid})
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4, len(jax.devices())   # 2 per process

# Cross-process collective: allgather each process's rank.
from jax.experimental import multihost_utils
got = multihost_utils.process_allgather(np.asarray([jax.process_index()]))
assert sorted(np.asarray(got).ravel().tolist()) == [0, 1], got

# A global mesh spanning both processes compiles + executes a psum.
mesh = make_mesh({{"dp": 4}})
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

x = jax.make_array_from_callback(
    (4,), NamedSharding(mesh, P("dp")),
    lambda idx: np.ones((1,), np.float32))
total = jax.jit(lambda a: jnp.sum(a),
                out_shardings=NamedSharding(mesh, P()))(x)
assert float(total) == 4.0, float(total)
print(f"proc {{jax.process_index()}} OK", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _clean_env():
    """Child env with a guaranteed-CPU jax: some dev images pre-import
    jax with a device plugin via a PYTHONPATH site hook BEFORE the
    child script runs, which latches the platform and (worse) its own
    distributed runtime — strip the hook and force CPU by env."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))
           and k not in ("PYTHONPATH", "PYTHONSTARTUP")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    return env


@pytest.mark.requires_tpu
@pytest.mark.skipif(os.environ.get("LLMQ_SKIP_MULTIPROC") == "1",
                    reason="multi-process test disabled")
def test_two_process_rendezvous_and_collective(tmp_path):
    coord = f"127.0.0.1:{_free_port()}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    procs = []
    try:
        for pid in range(2):
            script = _WORKER.format(repo=repo, coord=coord, pid=pid)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", script], env=_clean_env(),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out)
    finally:
        for p in procs:   # no leaked workers on rendezvous timeout
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
    assert any("proc 0 OK" in o for o in outs)
    assert any("proc 1 OK" in o for o in outs)


def test_bad_coordinator_fails_fast():
    """distributed_init must FAIL FAST on a genuinely bad setup, not
    swallow the error and limp along single-host (round-1 advisory).
    jax's client surfaces a dead coordinator as a fatal abort (absl
    FATAL from the coordination service) — either way the process must
    die with a distributed-error diagnostic, never print SWALLOWED."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = (
        "import os, sys\n"
        f"sys.path.insert(0, {repo!r})\n"
        "from llmq_tpu.parallel.mesh import distributed_init\n"
        "try:\n"
        "    distributed_init(coordinator='127.0.0.1:1',"
        " num_processes=2, process_id=1, initialization_timeout=5)\n"
        "except Exception:\n"
        "    print('RAISED', flush=True); raise SystemExit(0)\n"
        "print('SWALLOWED', flush=True); raise SystemExit(1)\n")
    env = _clean_env()
    p = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=90)
    out = p.stdout + p.stderr
    assert "SWALLOWED" not in out, out
    if "RAISED" not in out:   # fatal-abort path
        assert p.returncode != 0, out
        assert ("DEADLINE_EXCEEDED" in out
                or "CoordinationService" in out
                or "distributed service" in out), out


_TP_WORKER = r"""
import os, sys
import numpy as np

sys.path.insert(0, {repo!r})
import jax
import jax.numpy as jnp
from llmq_tpu.parallel.mesh import distributed_init, make_mesh

distributed_init(coordinator={coord!r}, num_processes=2,
                 process_id={pid}, initialization_timeout=60)
assert jax.process_count() == 2

from llmq_tpu.models.llama import (forward_decode, init_kv_pages,
                                   init_params, llama3_tiny)
from llmq_tpu.parallel.sharding import kv_cache_shardings, param_shardings
from jax.sharding import NamedSharding, PartitionSpec as P

# TP=4 across the two processes (2 devices each): a REAL cross-process
# tensor-parallel forward — the all-reduces after wo/w_down ride the
# inter-process transport (the DCN path of a multi-host v5e-16).
mesh = make_mesh({{"tp": 4}})
cfg = llama3_tiny(dim=128, n_layers=2, n_heads=8, n_kv_heads=4,
                  ffn_dim=256, vocab_size=256, max_seq_len=64,
                  dtype=jnp.float32)
params = init_params(jax.random.PRNGKey(0), cfg)   # identical per proc

def globalize(tree, shardings):
    return jax.tree.map(
        lambda x, s: jax.make_array_from_callback(
            x.shape, s, lambda idx: np.asarray(x)[idx]),
        tree, shardings)

gparams = globalize(params, param_shardings(cfg, mesh))
cache = init_kv_pages(cfg, 9, 8)
gcache = globalize(dict(cache), dict(kv_cache_shardings(cfg, mesh)))
repl = NamedSharding(mesh, P())
B = 2
tokens = np.array([3, 5], np.int32)
pos = np.zeros(B, np.int32)
bt = np.zeros((B, 8), np.int32)
bt[0, 0], bt[1, 0] = 1, 2
g = lambda x: jax.make_array_from_callback(  # noqa: E731
    x.shape, repl, lambda idx: x[idx])
logits, _ = forward_decode(gparams, cfg, g(tokens), g(pos), gcache, g(bt))
# GSPMD leaves the logits vocab-sharded (tp on the head); replicate so
# each process can read the full row locally.
logits = jax.jit(lambda x: x, out_shardings=repl)(logits)
tp_local = np.asarray(logits.addressable_shards[0].data)

# Single-process reference with the SAME weights, process-local.
ref_logits, _ = forward_decode(params, cfg, jnp.asarray(tokens),
                               jnp.asarray(pos),
                               init_kv_pages(cfg, 9, 8), jnp.asarray(bt))
ref = np.asarray(ref_logits)
assert np.allclose(tp_local, ref, atol=1e-4), np.abs(tp_local - ref).max()
print(f"proc {{jax.process_index()}} TP-forward OK", flush=True)
"""


@pytest.mark.requires_tpu
@pytest.mark.skipif(os.environ.get("LLMQ_SKIP_MULTIPROC") == "1",
                    reason="multi-process test disabled")
def test_two_process_tensor_parallel_forward(tmp_path):
    """Shard a real Llama forward tp=4 across two OS processes and check
    it against the single-process reference (VERDICT r3 weak #6: the
    2-process test covered dp only)."""
    coord = f"127.0.0.1:{_free_port()}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    try:
        for pid in range(2):
            script = _TP_WORKER.format(repo=repo, coord=coord, pid=pid)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", script], env=_clean_env(),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
    assert any("proc 0 TP-forward OK" in o for o in outs)
    assert any("proc 1 TP-forward OK" in o for o in outs)


@pytest.mark.skipif(os.environ.get("LLMQ_SKIP_MULTIPROC") == "1",
                    reason="multi-process test disabled")
def test_serve_entrypoints_join_cluster(tmp_path):
    """Two `python -m llmq_tpu gateway` processes with LLMQ_COORDINATOR
    env rendezvous into one jax.distributed cluster and both serve
    HTTP — the multi-host deployment path of docs/deployment.md."""
    coord_port = _free_port()
    ports = [_free_port() for _ in range(2)]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    try:
        for pid in range(2):
            env = _clean_env()
            env["PYTHONPATH"] = repo
            env["LLMQ_COORDINATOR"] = f"127.0.0.1:{coord_port}"
            env["LLMQ_NUM_PROCESSES"] = "2"
            env["LLMQ_PROCESS_ID"] = str(pid)
            env["LLMQ_CLUSTER_TIMEOUT"] = "60"
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "llmq_tpu", "--host", "127.0.0.1",
                 "--port", str(ports[pid]), "gateway"],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        import urllib.request
        deadline = 60
        import time as _t
        healthy = 0
        t0 = _t.time()
        while _t.time() - t0 < deadline and healthy < 2:
            healthy = 0
            for p in ports:
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{p}/health", timeout=2):
                        healthy += 1
                except OSError:
                    pass
            if healthy < 2:
                _t.sleep(0.5)
        assert healthy == 2, "gateways did not become healthy"
    finally:
        outs = []
        for p in procs:
            p.terminate()
            try:
                outs.append(p.communicate(timeout=20)[0])
            except subprocess.TimeoutExpired:
                p.kill()
                outs.append(p.communicate()[0])
    joined = [o for o in outs
              if "jax.distributed initialised" in o]
    assert len(joined) == 2, outs
