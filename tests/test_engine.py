"""Inference engine tests: allocator, tokenizer, echo end-to-end,
JAX-executor correctness, conversation KV reuse, preemption, pool
pressure, and the Worker process_fn seam.

The engine replaces the reference's simulated LLM processing
(cmd/queue-manager/main.go:139-153) behind the ProcessFunc seam
(worker.go:33); these tests are the evidence the seam is actually filled."""

import threading

import pytest

from llmq_tpu.core.clock import FakeClock
from llmq_tpu.core.types import Message, MessageStatus, Priority
from llmq_tpu.engine import (
    ByteTokenizer,
    EchoExecutor,
    GenRequest,
    InferenceEngine,
    JaxExecutor,
    PageAllocator,
)


# -- tokenizer ----------------------------------------------------------------

class TestByteTokenizer:
    def test_roundtrip(self):
        t = ByteTokenizer()
        for text in ("hello", "héllo wörld", "日本語", ""):
            assert t.decode(t.encode(text)) == text

    def test_ids_above_specials(self):
        t = ByteTokenizer()
        ids = t.encode("abc")
        assert all(i >= 3 for i in ids)
        assert t.vocab_size == 259


# -- allocator ----------------------------------------------------------------

class TestPageAllocator:
    def test_reserves_page_zero(self):
        a = PageAllocator(8, 16)
        got = set()
        while True:
            p = a.alloc(1)
            if p is None:
                break
            got.update(p)
        assert 0 not in got
        assert got == set(range(1, 8))

    def test_all_or_nothing(self):
        a = PageAllocator(5, 16)
        assert a.alloc(10) is None
        assert a.available() == 4  # nothing leaked
        pages = a.alloc(4)
        assert len(pages) == 4
        a.free(pages)
        assert a.available() == 4

    def test_pin_accounting(self):
        a = PageAllocator(8, 16)
        pages = a.alloc(3)
        a.pin("conv1", pages)
        assert a.pinned_pages() == 3
        back = a.unpin("conv1")
        assert back == pages
        assert a.pinned_pages() == 0

    def test_pages_for(self):
        assert PageAllocator.pages_for(1, 16) == 1
        assert PageAllocator.pages_for(16, 16) == 1
        assert PageAllocator.pages_for(17, 16) == 2


# -- echo engine --------------------------------------------------------------

def make_echo_engine(slots=4, num_pages=64, page_size=8, max_pages=16,
                     clock=None, **kw):
    tok = ByteTokenizer()
    ex = EchoExecutor(batch_size=slots, page_size=page_size,
                      num_pages=num_pages, max_pages_per_seq=max_pages,
                      eos_id=tok.eos_id)
    return InferenceEngine(ex, tok, enable_metrics=False, clock=clock, **kw)


class TestEchoEngine:
    def test_single_request_echoes(self):
        eng = make_echo_engine()
        h = eng.submit(GenRequest(id="r1", prompt="hello"))
        eng.run_until_idle()
        assert h.done
        assert h.result.text == "hello"
        assert h.result.finish_reason == "eos"
        assert h.result.prompt_tokens == 5
        # All pages returned to the pool.
        assert eng.allocator.used() == 0

    def test_batched_requests(self):
        eng = make_echo_engine(slots=4)
        prompts = [f"message-{i}" for i in range(10)]
        handles = [eng.submit(GenRequest(id=f"r{i}", prompt=p))
                   for i, p in enumerate(prompts)]
        eng.run_until_idle()
        for h, p in zip(handles, prompts):
            assert h.result.text == p
        assert eng.allocator.used() == 0

    def test_max_new_tokens_truncates(self):
        eng = make_echo_engine()
        h = eng.submit(GenRequest(id="r1", prompt="abcdefgh",
                                  max_new_tokens=3))
        eng.run_until_idle()
        assert h.result.text == "abc"
        assert h.result.finish_reason == "length"

    def test_cancellation(self):
        eng = make_echo_engine(slots=1)
        h1 = eng.submit(GenRequest(id="r1", prompt="x" * 50))
        h2 = eng.submit(GenRequest(id="r2", prompt="y" * 50))
        h2.cancel()
        eng.run_until_idle()
        assert h1.result.finish_reason == "eos"
        assert h2.result.finish_reason == "cancelled"

    def test_priority_order_single_slot(self):
        eng = make_echo_engine(slots=1)
        finish_order = []
        hs = {}
        for name, prio in (("low", Priority.LOW), ("rt", Priority.REALTIME),
                           ("norm", Priority.NORMAL)):
            hs[name] = eng.submit(GenRequest(id=name, prompt="zz",
                                             priority=prio))
        # Nothing admitted yet; first step admits in priority order.
        for _ in range(100):
            eng.step()
            for name, h in hs.items():
                if h.done and name not in finish_order:
                    finish_order.append(name)
            if len(finish_order) == 3:
                break
        assert finish_order == ["rt", "norm", "low"]


class TestPreemption:
    def test_realtime_preempts_low(self):
        eng = make_echo_engine(slots=1)
        hlow = eng.submit(GenRequest(id="low", prompt="L" * 40,
                                     priority=Priority.LOW))
        eng.step()  # admit low, first decode
        assert not hlow.done
        hrt = eng.submit(GenRequest(id="rt", prompt="R" * 4,
                                    priority=Priority.REALTIME))
        eng.run_until_idle()
        assert hrt.result.text == "R" * 4
        assert hlow.result.text == "L" * 40  # resumed and completed intact
        assert hrt.result.finish_reason == "eos"

    def test_no_preemption_when_disabled(self):
        eng = make_echo_engine(slots=1, preemption=False)
        hlow = eng.submit(GenRequest(id="low", prompt="L" * 40,
                                     priority=Priority.LOW))
        eng.step()
        eng.submit(GenRequest(id="rt", prompt="R" * 4,
                              priority=Priority.REALTIME))
        # Low finishes first because it cannot be displaced.
        for _ in range(200):
            eng.step()
            if hlow.done:
                break
        assert hlow.done

    def test_equal_priority_never_preempts(self):
        eng = make_echo_engine(slots=1)
        h1 = eng.submit(GenRequest(id="a", prompt="A" * 30,
                                   priority=Priority.HIGH))
        eng.step()
        h2 = eng.submit(GenRequest(id="b", prompt="B" * 5,
                                   priority=Priority.HIGH))
        for _ in range(200):
            eng.step()
            if h1.done and h2.done:
                break
        # FIFO within tier: a (earlier) completed before b started late.
        assert h1.done and h2.done


class TestConversationKV:
    def test_second_turn_reuses_cache(self):
        eng = make_echo_engine()
        h1 = eng.submit(GenRequest(id="t1", prompt="first turn",
                                   conversation_id="c1"))
        eng.run_until_idle()
        assert h1.result.cached_tokens == 0
        used_after_t1 = eng.allocator.used()
        assert used_after_t1 > 0  # pages stay pinned for the conversation
        assert eng.cached_conversations() == ["c1"]

        h2 = eng.submit(GenRequest(id="t2", prompt="second",
                                   conversation_id="c1"))
        eng.run_until_idle()
        assert h2.result.cached_tokens == len("first turn") + len("first turn")
        # turn-1 prompt + its echoed output are in the cache
        assert h2.result.text == "second"

    def test_conversation_eviction_frees_pages(self):
        eng = make_echo_engine()
        eng.submit(GenRequest(id="t1", prompt="hello", conversation_id="c1"))
        eng.run_until_idle()
        assert eng.allocator.used() > 0
        eng.drop_conversation("c1")
        assert eng.allocator.used() == 0
        assert eng.cached_conversations() == []

    def test_pin_ttl_expiry(self):
        clock = FakeClock()
        eng = make_echo_engine(clock=clock, kv_pin_ttl=10.0)
        eng.submit(GenRequest(id="t1", prompt="hello", conversation_id="c1"))
        eng.run_until_idle()
        assert eng.cached_conversations() == ["c1"]
        clock.advance(11.0)
        eng.step()
        assert eng.cached_conversations() == []
        assert eng.allocator.used() == 0

    def test_touch_refreshes_ttl(self):
        clock = FakeClock()
        eng = make_echo_engine(clock=clock, kv_pin_ttl=10.0)
        eng.submit(GenRequest(id="t1", prompt="hello", conversation_id="c1"))
        eng.run_until_idle()
        clock.advance(8.0)
        eng.touch_conversation("c1")
        clock.advance(8.0)
        eng.step()
        assert eng.cached_conversations() == ["c1"]  # touch reset the clock

    def test_overdue_low_beats_fresh_normal(self):
        """SLA-aware promotion (VERDICT r3 #9): a LOW request older than
        its tier's max_wait_time is promoted and admitted ahead of a
        NORMAL request that arrived later — without promotion, strict
        (priority, arrival) order would admit the normal first."""
        clock = FakeClock()
        eng = make_echo_engine(
            slots=1, clock=clock,
            tier_max_wait={Priority.LOW: 5.0})
        # Occupy the single slot so both contenders queue.
        blocker = eng.submit(GenRequest(id="block", prompt="x" * 40,
                                        priority=Priority.REALTIME))
        eng.step()
        assert blocker.done is False
        low = eng.submit(GenRequest(id="low", prompt="lo",
                                    priority=Priority.LOW))
        eng.step()             # low is pending, slot busy
        clock.advance(6.0)     # past LOW's max_wait → one-tier promotion
        normal = eng.submit(GenRequest(id="norm", prompt="no",
                                       priority=Priority.NORMAL))
        eng.run_until_idle()
        assert low.done and normal.done
        # Promoted low (effective NORMAL, earlier arrival) finished
        # before the fresh normal.
        assert low.finished_at < normal.finished_at

    def test_no_promotion_without_max_wait(self):
        """Same scenario, no tier_max_wait: strict priority order — the
        fresh normal beats the older low."""
        clock = FakeClock()
        eng = make_echo_engine(slots=1, clock=clock)
        blocker = eng.submit(GenRequest(id="block", prompt="x" * 40,
                                        priority=Priority.REALTIME))
        eng.step()
        low = eng.submit(GenRequest(id="low", prompt="lo",
                                    priority=Priority.LOW))
        eng.step()
        clock.advance(6.0)
        normal = eng.submit(GenRequest(id="norm", prompt="no",
                                       priority=Priority.NORMAL))
        eng.run_until_idle()
        assert normal.finished_at < low.finished_at
        del blocker

    def test_urgent_conv_turn_behind_preempted_holder_no_deadlock(self):
        """A conversation's turn 2 (urgent) must not deadlock admission
        when turn 1's sequence was preempted and sits BEHIND it in the
        pending queue (found by the randomized soak: the old
        head-of-line break left every slot idle forever)."""
        eng = make_echo_engine(slots=1)
        t1 = eng.submit(GenRequest(id="t1", prompt="turn one " + "x" * 30,
                                   priority=Priority.NORMAL,
                                   conversation_id="cc"))
        eng.step()                       # t1 admitted, starts prefill
        # A realtime non-conv request preempts t1 mid-generation.
        rt = eng.submit(GenRequest(id="rt", prompt="urgent",
                                   priority=Priority.REALTIME))
        # Turn 2 arrives REALTIME: more urgent than the preempted t1,
        # but must wait for it (turn order) without blocking the world.
        t2 = eng.submit(GenRequest(id="t2", prompt="turn two",
                                   priority=Priority.REALTIME,
                                   conversation_id="cc"))
        eng.run_until_idle()
        for h in (t1, rt, t2):
            assert h.done and h.result.finish_reason == "eos"
        # Turn order respected: t2 finished after t1.
        assert t2.finished_at > t1.finished_at
        assert t2.result.cached_tokens > 0   # and reused t1's KV

    def test_blocked_conv_turn_reserves_capacity_no_preemption(self):
        """preemption=False: a blocked urgent conversation turn must
        still RESERVE capacity — less urgent non-conversation work can't
        fill the slots in front of it (it would then wait out full LOW
        generations with no preemption to rescue it)."""
        eng = make_echo_engine(slots=2, preemption=False)
        t1 = eng.submit(GenRequest(id="t1", prompt="turn one " + "x" * 40,
                                   priority=Priority.NORMAL,
                                   conversation_id="cc"))
        eng.step()                      # t1 seated (slot 0)
        t2 = eng.submit(GenRequest(id="t2", prompt="turn two",
                                   priority=Priority.REALTIME,
                                   conversation_id="cc"))
        lows = [eng.submit(GenRequest(id=f"lo{i}", prompt="bg " + "y" * 50,
                                      priority=Priority.LOW))
                for i in range(3)]
        eng.run_until_idle()
        assert all(h.done for h in (t1, t2, *lows))
        # t2 ran before at least the later LOW requests: with 2 slots,
        # one LOW may ride alongside t1, but the reserved slot goes to
        # t2 the moment t1 finishes — t2 must beat the last low.
        assert t2.finished_at < max(lo.finished_at for lo in lows)

    def test_pool_pressure_evicts_lru_conversation(self):
        # 23 usable pages of 8 tokens; each conversation pins 8 pages
        # (30 prompt + 30 echo + 1), so the 16-page "big" request must
        # reclaim the LRU conversation (ca) to finish.
        eng = make_echo_engine(num_pages=24, page_size=8, max_pages=16)
        eng.submit(GenRequest(id="a", prompt="a" * 30, conversation_id="ca"))
        eng.run_until_idle()
        eng.submit(GenRequest(id="b", prompt="b" * 30, conversation_id="cb"))
        eng.run_until_idle()
        assert set(eng.cached_conversations()) == {"ca", "cb"}
        # A big non-conversation request forces LRU eviction of ca.
        h = eng.submit(GenRequest(id="big", prompt="x" * 60))
        eng.run_until_idle()
        assert h.result.text == "x" * 60
        assert "ca" not in eng.cached_conversations()

    def test_concurrent_same_conversation_serialised(self):
        eng = make_echo_engine(slots=4)
        h1 = eng.submit(GenRequest(id="t1", prompt="one", conversation_id="c"))
        h2 = eng.submit(GenRequest(id="t2", prompt="two", conversation_id="c"))
        eng.run_until_idle()
        assert h1.result.finish_reason == "eos"
        assert h2.result.finish_reason == "eos"
        # Turn 2 saw turn 1's cache (its tokens + echo).
        assert h2.result.cached_tokens == 2 * len("one")


class TestEngineThread:
    def test_background_loop_and_generate(self):
        eng = make_echo_engine()
        eng.start()
        try:
            res = eng.generate("threaded", timeout=10.0)
            assert res.text == "threaded"
        finally:
            eng.stop()
        assert not eng.running

    def test_process_fn_seam(self):
        """Worker drains the queue into the engine — the reference's
        ProcessFunc seam (worker.go:33) filled by real execution."""
        from llmq_tpu.queueing.queue_manager import QueueManager
        from llmq_tpu.queueing.worker import Worker

        eng = make_echo_engine()
        eng.start()
        qm = QueueManager("engine-test", enable_metrics=False)
        w = Worker("w0", qm, eng.process_fn)
        try:
            msgs = [Message(content=f"payload-{i}",
                            priority=Priority(1 + i % 4)) for i in range(8)]
            for m in msgs:
                qm.push_message(m)
            w.start()
            deadline = threading.Event()
            for _ in range(100):
                if all(m.status == MessageStatus.COMPLETED for m in msgs):
                    break
                deadline.wait(0.05)
            assert all(m.status == MessageStatus.COMPLETED for m in msgs)
            for m in msgs:
                assert m.response == m.content
                assert m.metadata["usage"]["completion_tokens"] > 0
        finally:
            w.stop()
            eng.stop()


class TestPagePressure:
    """Shedding-order correctness under pool exhaustion."""

    def test_low_request_cannot_strip_realtime_pages(self):
        # 7 usable pages of 8 tokens. A realtime sequence occupies most
        # of the pool; a LOW request that cannot fit must WAIT, not
        # preempt-with-release the more urgent runner.
        eng = make_echo_engine(slots=2, num_pages=8, page_size=8,
                               max_pages=8)
        victims = []
        orig = eng._preempt
        eng._preempt = lambda v, release_pages: (
            victims.append((v.req.id, release_pages)),
            orig(v, release_pages))[-1]
        hrt = eng.submit(GenRequest(id="rt", prompt="R" * 24,
                                    priority=Priority.REALTIME))
        eng.step()  # admit rt: 25-token footprint → 4 pages
        hlow = eng.submit(GenRequest(id="low", prompt="L" * 24,
                                     priority=Priority.LOW))
        eng.step()
        assert not hlow.done
        assert eng.get_stats()["active"] == 1  # low is waiting, not admitted
        eng.run_until_idle()
        assert hrt.result.text == "R" * 24
        assert hlow.result.text == "L" * 24
        assert ("rt", True) not in victims  # realtime never stripped

    def test_pending_held_pages_are_reclaimable(self):
        # One slot: LOW gets slot-preempted by HIGH (keeps pages), then
        # REALTIME needs those parked pages — shedding must find them
        # rather than deadlock.
        eng = make_echo_engine(slots=1, num_pages=12, page_size=8,
                               max_pages=12)
        hlow = eng.submit(GenRequest(id="low", prompt="L" * 40,
                                     priority=Priority.LOW))
        eng.step()  # low admitted, holds ~6 pages
        hhigh = eng.submit(GenRequest(id="h", prompt="H" * 30,
                                      priority=Priority.HIGH))
        hrt = eng.submit(GenRequest(id="rt", prompt="R" * 30,
                                    priority=Priority.REALTIME))
        eng.run_until_idle()
        assert hrt.result.text == "R" * 30
        assert hhigh.result.text == "H" * 30
        assert hlow.result.text == "L" * 40  # rebuilt after page loss
        assert eng.allocator.used() == 0

    def test_released_conversation_turn_rebuilds_history(self):
        """A conversation sequence whose pages are reclaimed mid-turn
        must rebuild with its full adopted history, not just the turn's
        prompt (echo streams history+prompt, so the echoed text proves
        what context the rebuild saw)."""
        eng = make_echo_engine(slots=1, num_pages=16, page_size=8,
                               max_pages=16)
        h1 = eng.submit(GenRequest(id="t1", prompt="hist", max_new_tokens=4,
                                   conversation_id="c", priority=Priority.LOW))
        eng.run_until_idle()
        assert h1.result.text == "hist"
        # Turn 2 adopts the cache, then is preempted-with-release by a
        # realtime burst big enough to need its pages.
        h2 = eng.submit(GenRequest(id="t2", prompt="-two",
                                   conversation_id="c", priority=Priority.LOW))
        eng.step()  # admit turn 2 (adopts cache)
        # 15 usable pages = 120 tokens; rt needs 105 (14 pages) which
        # forces reclaiming t2's adopted pages but still fits the pool.
        hrt = eng.submit(GenRequest(id="rt", prompt="X" * 52,
                                    priority=Priority.REALTIME))
        eng.run_until_idle()
        assert hrt.result.text == "X" * 52
        # Echo replays the prefill stream it saw: turn 1 ended by length,
        # so its pending token 't' leads turn 2's stream ("t-two"). A
        # rebuild that lost the adopted context or misaligned the echo
        # would produce a different string.
        assert h2.result.text == "t-two"
        assert h2.result.finish_reason == "eos"
        assert eng.allocator.used() >= 0


class TestChunkedDecode:
    """decode_chunk semantics: K steps per call must be indistinguishable
    from K single steps (EOS latching, budgets, page accounting)."""

    def test_echo_chunked_equals_single(self):
        for prompt in ("hello", "a" * 23, "xy"):
            e1 = make_echo_engine(slots=2)
            tok = ByteTokenizer()
            ex = EchoExecutor(batch_size=2, page_size=8, num_pages=64,
                              max_pages_per_seq=16, eos_id=tok.eos_id,
                              chunk_size=4)
            ek = InferenceEngine(ex, tok, enable_metrics=False)
            h1 = e1.submit(GenRequest(id="r", prompt=prompt))
            hk = ek.submit(GenRequest(id="r", prompt=prompt))
            e1.run_until_idle()
            ek.run_until_idle()
            assert hk.result.text == h1.result.text == prompt
            assert hk.result.finish_reason == h1.result.finish_reason
            assert ek.allocator.used() == 0

    def test_chunked_respects_max_new_tokens(self):
        tok = ByteTokenizer()
        ex = EchoExecutor(batch_size=1, page_size=8, num_pages=64,
                          max_pages_per_seq=16, eos_id=tok.eos_id,
                          chunk_size=8)
        eng = InferenceEngine(ex, tok, enable_metrics=False)
        h = eng.submit(GenRequest(id="r", prompt="abcdefghij",
                                  max_new_tokens=3))
        eng.run_until_idle()
        assert h.result.text == "abc"
        assert h.result.finish_reason == "length"

    def test_chunked_conversation_pending_token(self):
        """A length-finish inside a chunk leaves the final token's KV
        unwritten; the next turn must carry it (same as single-step)."""
        tok = ByteTokenizer()
        ex = EchoExecutor(batch_size=1, page_size=8, num_pages=64,
                          max_pages_per_seq=16, eos_id=tok.eos_id,
                          chunk_size=4)
        eng = InferenceEngine(ex, tok, enable_metrics=False)
        h1 = eng.submit(GenRequest(id="t1", prompt="abcdef",
                                   conversation_id="c", max_new_tokens=6))
        eng.run_until_idle()
        assert h1.result.finish_reason == "length"
        h2 = eng.submit(GenRequest(id="t2", prompt="gh",
                                   conversation_id="c"))
        eng.run_until_idle()
        assert h2.result.finish_reason == "eos"
        assert h2.result.cached_tokens > 0


# -- JAX executor -------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_model():
    import jax

    from llmq_tpu.models.llama import init_params, llama3_tiny

    cfg = llama3_tiny(dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                      ffn_dim=128, vocab_size=512, max_seq_len=256)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def make_jax_engine(tiny_model, slots=2, num_pages=64, page_size=8, **kw):
    cfg, params = tiny_model
    tok = ByteTokenizer()
    ex = JaxExecutor(cfg, params, batch_size=slots, page_size=page_size,
                     num_pages=num_pages, prefill_buckets=[16, 64],
                     eos_id=tok.eos_id)
    return InferenceEngine(ex, tok, enable_metrics=False,
                           max_decode_steps=8, **kw)


def reference_greedy(cfg, params, prompt_ids, n_steps):
    """Dense single-sequence greedy decode, independent of the engine."""
    import jax.numpy as jnp

    from llmq_tpu.models.llama import forward_decode, forward_prefill, init_kv_pages

    page_size = 8
    pages = init_kv_pages(cfg, 64, page_size)
    max_pages = 32
    bt = jnp.arange(1, max_pages + 1, dtype=jnp.int32)[None, :]
    n = len(prompt_ids)
    toks = jnp.asarray(prompt_ids, jnp.int32)[None, :]
    pos = jnp.arange(n, dtype=jnp.int32)[None, :]
    logits, pages = forward_prefill(params, cfg, toks, pos,
                                    jnp.asarray([n], jnp.int32), pages, bt)
    out = [int(jnp.argmax(logits[0, n - 1]))]
    cur = out[0]
    for i in range(n_steps - 1):
        lg, pages = forward_decode(
            params, cfg, jnp.asarray([cur], jnp.int32),
            jnp.asarray([n + i], jnp.int32), pages, bt)
        cur = int(jnp.argmax(lg[0]))
        out.append(cur)
    return out


class TestJaxEngine:
    def test_multi_chunk_generation_spans_chunks(self, tiny_model):
        """A generation LONGER than chunk_size must produce identical
        tokens through the pipelined/speculative path (chunk_size=4) and
        the single-step path (chunk_size=1) — and run to its full length
        (r4: a carry bug latched budget-paused rows as done, truncating
        every multi-chunk generation with a phantom EOS)."""
        cfg, params = tiny_model
        tok = ByteTokenizer()

        def run(chunk):
            ex = JaxExecutor(cfg, params, batch_size=2, page_size=8,
                             num_pages=64, prefill_buckets=[16, 64],
                             eos_id=tok.eos_id, chunk_size=chunk)
            eng = InferenceEngine(ex, tok, enable_metrics=False,
                                  max_decode_steps=64)
            h = eng.submit(GenRequest(id="r", prompt="span the chunks",
                                      max_new_tokens=20))
            eng.run_until_idle()
            return h.result

        piped = run(4)      # 20 tokens span 5 chunks
        single = run(1)
        assert piped.tokens == single.tokens
        if piped.finish_reason == "length":
            assert len(piped.tokens) == 20

    def test_pipelined_soak_randomized(self, tiny_model):
        """Randomized soak of the pipelined engine: mixed priorities,
        conversations, multi-chunk generations and mid-flight
        cancellations. Invariants at idle: every handle resolved, page
        accounting balances (used == pinned conversation pages), no
        sequence state leaked."""
        import random as _random

        cfg, params = tiny_model
        tok = ByteTokenizer()
        ex = JaxExecutor(cfg, params, batch_size=3, page_size=8,
                         num_pages=96, prefill_buckets=[16, 64],
                         eos_id=tok.eos_id, chunk_size=4)
        eng = InferenceEngine(ex, tok, enable_metrics=False,
                              max_decode_steps=24, kv_pin_ttl=0)
        rng = _random.Random(123)
        prios = [Priority.REALTIME, Priority.HIGH, Priority.NORMAL,
                 Priority.LOW]
        handles = []
        for i in range(40):
            conv = f"c{rng.randrange(6)}" if rng.random() < 0.4 else ""
            h = eng.submit(GenRequest(
                id=f"s{i}", prompt=f"prompt {i} " + "x" * rng.randrange(40),
                priority=rng.choice(prios), conversation_id=conv,
                max_new_tokens=rng.randrange(1, 20)))
            handles.append(h)
            # Interleave scheduling with arrivals + random cancels.
            for _ in range(rng.randrange(4)):
                eng.step()
            if rng.random() < 0.15:
                rng.choice(handles).cancel()
        eng.run_until_idle()
        assert all(h.done for h in handles)
        for h in handles:
            assert h.result.finish_reason in ("eos", "length",
                                              "cancelled"), h.result
        # Page accounting: everything not pinned to a conversation is
        # back in the pool.
        assert eng.allocator.used() == eng.allocator.pinned_pages()
        assert all(s is None for s in eng._slots)
        assert eng._chunk_inflight is None
        assert not eng._pending and not eng._inbox
        # Conversations still answer a follow-up turn correctly.
        convs = eng.cached_conversations()
        if convs:
            h2 = eng.submit(GenRequest(id="follow", prompt=" more",
                                       conversation_id=convs[0],
                                       max_new_tokens=4))
            eng.run_until_idle()
            assert h2.result.finish_reason in ("eos", "length")
            assert h2.result.cached_tokens > 0

    def test_preemption_defers_while_chunk_inflight(self, tiny_model):
        """Pipelined executor: a realtime arrival while low-tier chunks
        are in flight must still preempt and finish first — preemption
        is DEFERRED to the reconcile (never applied to rows the device
        is still decoding), not dropped."""
        cfg, params = tiny_model
        tok = ByteTokenizer()
        ex = JaxExecutor(cfg, params, batch_size=1, page_size=8,
                         num_pages=64, prefill_buckets=[16, 64],
                         eos_id=tok.eos_id, chunk_size=4)
        eng = InferenceEngine(ex, tok, enable_metrics=False,
                              max_decode_steps=40)
        low = eng.submit(GenRequest(id="low", prompt="background work",
                                    priority=Priority.LOW,
                                    max_new_tokens=40))
        # Steps until a chunk is in flight for the low request.
        for _ in range(50):
            eng.step()
            if eng._chunk_inflight is not None:
                break
        assert eng._chunk_inflight is not None
        rt = eng.submit(GenRequest(id="rt", prompt="urgent",
                                   priority=Priority.REALTIME,
                                   max_new_tokens=4))
        eng.run_until_idle()
        assert rt.result.finish_reason in ("eos", "length")
        assert low.result.finish_reason in ("eos", "length")
        # The realtime request finished BEFORE the preempted low one.
        assert rt.finished_at < low.finished_at
        # And the preempted low request still produced its full output.
        if low.result.finish_reason == "length":
            assert len(low.result.tokens) == 40

    def test_batched_prefill_matches_sequential(self, tiny_model):
        """An admission wave through the batched-prefill program
        (prefill_batch=4) must produce exactly the tokens the
        one-sequence-per-program path produces, including a
        continuation turn over cached KV."""
        cfg, params = tiny_model
        tok = ByteTokenizer()
        prompts = ["alpha prompt one", "beta two", "gamma three is longer",
                   "delta", "epsilon five"]

        def run(npf):
            ex = JaxExecutor(cfg, params, batch_size=8, page_size=8,
                             num_pages=128, prefill_buckets=[16, 64],
                             eos_id=tok.eos_id, chunk_size=4,
                             prefill_batch=npf)
            eng = InferenceEngine(ex, tok, enable_metrics=False,
                                  max_decode_steps=6)
            hs = [eng.submit(GenRequest(id=f"r{i}", prompt=p,
                                        conversation_id=f"c{i}",
                                        max_new_tokens=6))
                  for i, p in enumerate(prompts)]
            eng.run_until_idle()
            first = [h.result.tokens for h in hs]
            # Turn 2: continuation prefill over the cached KV.
            h2 = eng.submit(GenRequest(id="t2", prompt=" more",
                                       conversation_id="c0",
                                       max_new_tokens=6))
            eng.run_until_idle()
            assert h2.result.cached_tokens > 0
            return first, h2.result.tokens

        batched, b2 = run(4)
        single, s2 = run(1)
        assert batched == single
        assert b2 == s2

    def test_greedy_matches_reference(self, tiny_model):
        cfg, params = tiny_model
        eng = make_jax_engine(tiny_model)
        prompt = "hello world"
        h = eng.submit(GenRequest(id="r", prompt=prompt, max_new_tokens=6))
        eng.run_until_idle()
        got = h.result.tokens
        tok = ByteTokenizer()
        want = reference_greedy(cfg, params, tok.encode(prompt), 6)
        # EOS may cut the engine's output short; compare the prefix.
        assert got == want[: len(got)]
        assert len(got) >= 1

    def test_batched_equals_single(self, tiny_model):
        """Continuous batching must not change any sequence's tokens."""
        eng2 = make_jax_engine(tiny_model, slots=2)
        prompts = ["alpha beta", "gamma delta epsilon"]
        hs = [eng2.submit(GenRequest(id=f"r{i}", prompt=p, max_new_tokens=5))
              for i, p in enumerate(prompts)]
        eng2.run_until_idle()

        for p, h in zip(prompts, hs):
            eng1 = make_jax_engine(tiny_model, slots=1)
            h1 = eng1.submit(GenRequest(id="solo", prompt=p, max_new_tokens=5))
            eng1.run_until_idle()
            assert h.result.tokens == h1.result.tokens

    def test_conversation_continuation_matches_full_prefill(self, tiny_model):
        """Turn 2 on cached KV must produce the same tokens as prefilling
        the whole history from scratch (numeric KV-reuse correctness)."""
        t1, t2 = "abc", "defg"
        # Engine A: two turns through the conversation cache.
        engA = make_jax_engine(tiny_model)
        h1 = engA.submit(GenRequest(id="t1", prompt=t1, conversation_id="c",
                                    max_new_tokens=4))
        engA.run_until_idle()
        h2 = engA.submit(GenRequest(id="t2", prompt=t2, conversation_id="c",
                                    max_new_tokens=4))
        engA.run_until_idle()
        assert h2.result.cached_tokens > 0

        # Engine B: one shot over the concatenated history.
        tok = ByteTokenizer()
        history = tok.encode(t1) + h1.result.tokens + tok.encode(t2)
        cfg, params = tiny_model
        want = reference_greedy(cfg, params, history, 4)
        got = h2.result.tokens
        assert got == want[: len(got)]

    def test_long_prompt_chunked_prefill(self, tiny_model):
        """Prompts beyond the largest bucket stream through it in chunks."""
        cfg, params = tiny_model
        eng = make_jax_engine(tiny_model)  # buckets [16, 64]
        prompt = "x" * 100                 # > 64 → two chunks
        h = eng.submit(GenRequest(id="r", prompt=prompt, max_new_tokens=3))
        eng.run_until_idle()
        tok = ByteTokenizer()
        want = reference_greedy(cfg, params, tok.encode(prompt), 3)
        assert h.result.tokens == want[: len(h.result.tokens)]


class _ChunkSpyExecutor(EchoExecutor):
    """Echo executor that exposes prefill buckets and records the
    interleaving of prefill chunks and decode steps."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.prefill_buckets = [4]       # tiny bucket → many chunks
        self.trace: list = []
        self._partial: dict = {}

    def prefill(self, tokens, start_pos, block_table, temperature, slot):
        self.trace.append(("prefill", slot, len(tokens)))
        # Accumulate chunks so the echo stream is the FULL prompt.
        if slot in self._partial and self._partial[slot][1] == start_pos:
            prev, _ = self._partial[slot]
            tokens = prev + list(tokens)
            start_pos = start_pos - len(prev)
        first = super().prefill(tokens, start_pos, block_table,
                                temperature, slot)
        self._partial[slot] = (list(tokens),
                               start_pos + len(tokens))
        return first

    def decode(self, tokens, positions, block_tables, temperatures):
        self.trace.append(("decode",))
        return super().decode(tokens, positions, block_tables,
                              temperatures)


class TestIncrementalPrefill:
    def test_long_prompt_interleaves_with_decode(self):
        """A long prompt admitted while another sequence decodes must
        NOT stall it: prefill buckets and decode steps alternate."""
        tok = ByteTokenizer()
        ex = _ChunkSpyExecutor(batch_size=2, page_size=4, num_pages=64,
                               max_pages_per_seq=16, eos_id=tok.eos_id)
        eng = InferenceEngine(ex, tok, enable_metrics=False,
                              max_decode_steps=12)
        # Sequence A: 16-token prompt (4 prefill buckets), 16-token echo
        # → keeps decoding while B prefills.
        ha = eng.submit(GenRequest(id="a", prompt="a" * 16,
                                   max_new_tokens=30))
        for _ in range(6):   # 4 prefill buckets + a couple decode steps
            eng.step()
        assert any(t[0] == "decode" for t in ex.trace)
        # Sequence B: 30-token prompt → 8 buckets of 4 on slot 1.
        hb = eng.submit(GenRequest(id="b", prompt="x" * 30,
                                   max_new_tokens=4))
        eng.run_until_idle()
        assert ha.done and hb.done
        assert ha.result.finish_reason in ("eos", "length")
        assert hb.result.finish_reason in ("eos", "length")
        # B's prompt ran as multiple bucket chunks...
        b_chunks = [t for t in ex.trace if t[0] == "prefill" and t[1] == 1]
        assert len(b_chunks) >= 8, ex.trace
        # ...and decode steps happened BETWEEN them (no stall).
        first_b = ex.trace.index(b_chunks[0])
        last_b = ex.trace.index(b_chunks[-1])
        between = ex.trace[first_b:last_b]
        assert any(t[0] == "decode" for t in between), ex.trace
        # Echo correctness survives chunked prefill: b echoes its prompt.
        assert hb.result.text == "xxxx", hb.result

    def test_mid_prefill_not_preemptible(self):
        """A realtime arrival must not strip a mid-prefill sequence's
        slot (partial state can't restart); it waits for a real victim."""
        tok = ByteTokenizer()
        ex = _ChunkSpyExecutor(batch_size=1, page_size=4, num_pages=64,
                               max_pages_per_seq=16, eos_id=tok.eos_id)
        eng = InferenceEngine(ex, tok, enable_metrics=False,
                              max_decode_steps=4)
        hb = eng.submit(GenRequest(id="slow", prompt="y" * 20,
                                   priority=Priority.LOW,
                                   max_new_tokens=2))
        eng.step()                         # admitted, first bucket runs
        hr = eng.submit(GenRequest(id="rt", prompt="hi",
                                   priority=Priority.REALTIME,
                                   max_new_tokens=2))
        eng.step()                         # rt pending; slow keeps slot
        assert not hb.done
        eng.run_until_idle()
        assert hb.done and hr.done
        assert hb.result.finish_reason in ("eos", "length")
        assert hr.result.finish_reason in ("eos", "length")

    def test_pool_pressure_strips_midprefill_low_tier(self):
        """Priority inversion guard: a LOW sequence mid-prefill must
        yield its pages when a REALTIME decoding sequence needs one —
        and later restart via the rebuild path with its full prompt."""
        tok = ByteTokenizer()
        # Pool: 15 usable pages of 4 slots = 60 tokens.
        ex = _ChunkSpyExecutor(batch_size=2, page_size=4, num_pages=16,
                               max_pages_per_seq=16, eos_id=tok.eos_id)
        eng = InferenceEngine(ex, tok, enable_metrics=False,
                              max_decode_steps=24)
        # Realtime: 12-token prompt (3 buckets), echoes 12 tokens.
        hr = eng.submit(GenRequest(id="rt", prompt="r" * 12,
                                   priority=Priority.REALTIME,
                                   max_new_tokens=20))
        for _ in range(4):
            eng.step()                    # rt prefilled, starts decoding
        assert any(t[0] == "decode" for t in ex.trace)
        # Low: 40-token prompt grabs most remaining pages, mid-prefill.
        hl = eng.submit(GenRequest(id="lo", prompt="l" * 40,
                                   priority=Priority.LOW,
                                   max_new_tokens=4))
        eng.step()                        # low admitted, 1st bucket only
        # Drive to completion: rt will need new pages for decode growth;
        # the pool is exhausted → low's pages must be reclaimable.
        eng.run_until_idle()
        assert hr.done and hr.result.finish_reason in ("eos", "length")
        assert hr.result.text == "r" * 12, hr.result   # echo intact
        assert hl.done and hl.result.finish_reason in ("eos", "length")
        assert hl.result.text == "l" * 4, hl.result    # rebuilt correctly
