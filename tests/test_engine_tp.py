"""Engine-level tensor-parallel serving (VERDICT r3 missing #1).

The full serving stack — InferenceEngine → JaxExecutor(mesh) → sharded
model → sampled tokens — on the virtual 8-device CPU mesh: params and
the KV pool are genuinely partitioned over the ``tp`` axis (asserted on
the arrays), and the engine's output must be IDENTICAL to the
single-device engine (greedy, same weights). Covers bf16 and int8
(ADVICE r3: quantization must thread into param_shardings on the mesh
path), plus the builder's ``tpu.mesh_shape`` wiring.
"""

import jax

from llmq_tpu.core.types import Priority
from llmq_tpu.engine.engine import GenRequest, InferenceEngine
from llmq_tpu.engine.executor import JaxExecutor
from llmq_tpu.engine.tokenizer import ByteTokenizer
from llmq_tpu.models.llama import init_params, llama3_tiny
from llmq_tpu.parallel import make_mesh


def tp_cfg(**kw):
    # KV heads divisible by 8 so the tp sharding is REAL on every axis
    # (the tiny default's 2 KV heads would silently replicate).
    defaults = dict(dim=256, n_heads=8, n_kv_heads=8, ffn_dim=512,
                    vocab_size=512, max_seq_len=256)
    defaults.update(kw)
    return llama3_tiny(**defaults)


def build_engine_pair(params, cfg, mesh):
    tok = ByteTokenizer()
    kw = dict(batch_size=4, page_size=16, num_pages=65, chunk_size=4,
              prefill_buckets=[32], eos_id=tok.eos_id)
    ex_tp = JaxExecutor(cfg, params, mesh=mesh, **kw)
    ex_1 = JaxExecutor(cfg, params, **kw)
    eng_tp = InferenceEngine(ex_tp, tok, name="tp", enable_metrics=False,
                             max_decode_steps=8)
    eng_1 = InferenceEngine(ex_1, tok, name="one", enable_metrics=False,
                            max_decode_steps=8)
    return eng_tp, eng_1, ex_tp


def run_requests(engine, reqs):
    handles = [engine.submit(GenRequest(**r)) for r in reqs]
    engine.run_until_idle()
    return [h.result for h in handles]


REQS = [
    dict(id="a", prompt="hello tensor parallel", conversation_id="c1"),
    dict(id="b", prompt="second request", priority=Priority.REALTIME),
    dict(id="c", prompt="third one", conversation_id="c2"),
]


class TestShardedServing:
    def test_tp8_engine_matches_single_device(self):
        mesh = make_mesh({"tp": 8})
        cfg = tp_cfg()
        params = init_params(jax.random.PRNGKey(0), cfg)
        eng_tp, eng_1, ex_tp = build_engine_pair(params, cfg, mesh)

        # The sharding is real: wq's output axis and the pool's KV-head
        # axis are split 8 ways.
        wq = ex_tp.params["layers"]["wq"]
        assert wq.sharding.spec == jax.sharding.PartitionSpec(
            None, None, "tp")
        shard_shapes = {s.data.shape for s in wq.addressable_shards}
        assert shard_shapes == {(cfg.n_layers, cfg.dim, cfg.dim // 8)}
        kv = ex_tp.cache["k"]
        assert kv.addressable_shards[0].data.shape[-1] == (
            kv.shape[-1] // 8)

        res_tp = run_requests(eng_tp, REQS)
        res_1 = run_requests(eng_1, REQS)
        for r_tp, r_1 in zip(res_tp, res_1):
            assert r_tp.finish_reason in ("eos", "length")
            assert r_tp.tokens == r_1.tokens
            assert r_tp.text == r_1.text

        # Turn 2 on a cached conversation: continuation prefill over the
        # SHARDED pool must also match.
        t2_tp = run_requests(eng_tp, [dict(id="a2", prompt=" more",
                                           conversation_id="c1")])[0]
        t2_1 = run_requests(eng_1, [dict(id="a2", prompt=" more",
                                         conversation_id="c1")])[0]
        assert t2_tp.cached_tokens > 0
        assert t2_tp.cached_tokens == t2_1.cached_tokens
        assert t2_tp.tokens == t2_1.tokens

    def test_tp8_int8_engine(self):
        """ADVICE r3: int8 + mesh must compose — quantized {q,s} leaves
        get the same named-axis shardings as the bf16 weights."""
        from llmq_tpu.ops.quant import quantize_params

        mesh = make_mesh({"tp": 8})
        cfg = tp_cfg()
        params = quantize_params(init_params(jax.random.PRNGKey(0), cfg))
        eng_tp, eng_1, ex_tp = build_engine_pair(params, cfg, mesh)
        wq = ex_tp.params["layers"]["wq"]
        assert wq["q"].sharding.spec == jax.sharding.PartitionSpec(
            None, None, "tp")
        assert wq["s"].sharding.spec == jax.sharding.PartitionSpec(
            None, None, "tp")
        res_tp = run_requests(eng_tp, REQS)
        res_1 = run_requests(eng_1, REQS)
        for r_tp, r_1 in zip(res_tp, res_1):
            assert r_tp.finish_reason in ("eos", "length")
            assert r_tp.tokens == r_1.tokens

    def test_dp_tp_mesh_also_serves(self):
        """A dp×tp mesh (the multi-host shape) serves correctly: dp is
        simply unused by the executor's shardings (engine replication
        handles data parallelism), tp partitions as usual."""
        mesh = make_mesh({"dp": 2, "tp": 4})
        cfg = tp_cfg()
        params = init_params(jax.random.PRNGKey(0), cfg)
        eng_tp, eng_1, _ = build_engine_pair(params, cfg, mesh)
        res_tp = run_requests(eng_tp, REQS[:2])
        res_1 = run_requests(eng_1, REQS[:2])
        for r_tp, r_1 in zip(res_tp, res_1):
            assert r_tp.tokens == r_1.tokens

    def test_builder_mesh_shape_wiring(self):
        """config.tpu.mesh_shape builds a meshed executor end-to-end."""
        from llmq_tpu.core.config import default_config
        from llmq_tpu.engine.builder import build_engine

        cfg = default_config()
        cfg.executor.backend = "jax"
        cfg.executor.max_batch_size = 2
        cfg.executor.kv_pages = 33
        cfg.executor.decode_chunk = 2
        cfg.executor.prefill_buckets = [32]
        cfg.model.name = "llama3-tiny"
        cfg.model.max_seq_len = 128
        cfg.tpu.mesh_shape = {"tp": 8}
        engine = build_engine(cfg, warmup=False, enable_metrics=False)
        assert engine.executor.mesh is not None
        res = run_requests(engine, [dict(id="x", prompt="hi")])[0]
        assert res.finish_reason in ("eos", "length")
