"""Entrypoint wiring tests (``python -m llmq_tpu``).

The reference's monolith leaves worker creation as a TODO
(cmd/server/main.go:172-193) and its gateway/consumer build disjoint
in-process queues; these tests pin down that our wiring actually drains
what it accepts."""

from __future__ import annotations

import json
import time
import urllib.request

from llmq_tpu.__main__ import App, main
from llmq_tpu.core.config import default_config


def _post(port: int, path: str, body: dict) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def _get(port: int, path: str) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return json.loads(r.read())


def test_monolith_serves_and_drains():
    cfg = default_config()
    cfg.server.host = "127.0.0.1"
    cfg.server.port = 0
    cfg.queue.enable_metrics = False
    cfg.queue.worker.process_interval = 0.005
    cfg.loadbalancer.health_check_interval = 0.0
    app = App(cfg, with_api=True, with_workers=True, with_engine=True,
              with_scheduler=True)
    app.start()
    try:
        port = app.api._httpd.server_address[1]
        out = _post(port, "/api/v1/messages",
                    {"content": "end to end", "user_id": "t"})
        mid = out["message_id"]
        deadline = time.time() + 15
        status = ""
        while time.time() < deadline:
            m = _get(port, f"/api/v1/messages/{mid}")
            status = m["status"]
            if status == "completed":
                break
            time.sleep(0.02)
        assert status == "completed"
        assert m["response"]
        # Monolith created the reference's three managers.
        stats = _get(port, "/api/v1/queues/stats")
        assert {"standard", "delayed", "priority"} <= set(stats)
    finally:
        app.stop()


def test_consumer_daemon_drains_without_api():
    from llmq_tpu.core.types import Message

    cfg = default_config()
    cfg.queue.enable_metrics = False
    cfg.queue.worker.process_interval = 0.005
    cfg.loadbalancer.health_check_interval = 0.0
    app = App(cfg, with_api=False, with_workers=True, with_engine=True)
    app.start()
    try:
        assert app.api is None
        mgr = app.factory.get_queue_manager("standard")
        msg = Message(id="c1", content="consume me", user_id="t")
        mgr.push_message(msg)
        deadline = time.time() + 15
        while time.time() < deadline and not msg.response:
            time.sleep(0.02)
        assert msg.response
    finally:
        app.stop()


def test_check_command_exit_code():
    assert main(["--backend", "echo", "check"]) == 0
