"""QueueFactory tests.

Mirrors reference tests/queue_factory_test.go:42-211 (manager creation per
type, idempotent get, worker creation/stop, cleanup) plus the wiring the
reference's empty switch arms lack."""

import pytest

from llmq_tpu.core.types import Message, Priority
from llmq_tpu.queueing.factory import (
    QueueFactory,
    QueueType,
)


@pytest.fixture
def factory(fake_clock, queue_backend) -> QueueFactory:
    f = QueueFactory(clock=fake_clock, backend=queue_backend)
    yield f
    f.stop_all()


class TestManagers:
    def test_create_idempotent(self, factory):
        m1 = factory.create_queue_manager("a", start_background=False)
        m2 = factory.create_queue_manager("a", start_background=False)
        assert m1 is m2
        assert factory.manager_names() == ["a"]

    def test_every_manager_fully_wired(self, factory):
        # Fixes the reference's empty delayed/dead_letter arms
        # (queue_factory.go:193-200).
        factory.create_queue_manager("std", QueueType.STANDARD,
                                     start_background=False)
        assert factory.get_delayed_queue("std") is not None
        assert factory.get_dead_letter_queue("std") is not None

    def test_priority_type_installs_demo_rules(self, factory):
        # VIP → HIGH; >10k chars → LOW (queue_factory.go:211-233).
        m = factory.create_queue_manager("p", QueueType.PRIORITY,
                                         start_background=False)
        rules = {r.name for r in m.list_priority_rules()}
        assert rules == {"vip_boost", "long_content_demote"}

        vip = Message(content="hi", priority=Priority.LOW, metadata={"vip": True})
        m.push_message(vip)
        assert vip.priority == Priority.HIGH

        longmsg = Message(content="x" * 10_001, priority=Priority.NORMAL)
        m.push_message(longmsg)
        assert longmsg.priority == Priority.LOW

    def test_standard_type_has_no_rules(self, factory):
        m = factory.create_queue_manager("s", QueueType.STANDARD,
                                         start_background=False)
        assert m.list_priority_rules() == []

    def test_get_missing_returns_none(self, factory):
        assert factory.get_queue_manager("nope") is None

    def test_remove(self, factory):
        factory.create_queue_manager("gone", start_background=False)
        assert factory.remove_queue_manager("gone")
        assert not factory.remove_queue_manager("gone")
        assert factory.get_queue_manager("gone") is None


class TestWorkers:
    def test_create_workers_and_stats(self, factory):
        factory.create_queue_manager("w", start_background=False)
        workers = factory.create_workers("w", 2, lambda ctx, msg: None,
                                         start=False)
        assert len(workers) == 2
        stats = factory.get_worker_stats("w")
        assert set(stats) == {"w-w0", "w-w1"}
        assert stats["w-w0"]["processed"] == 0

    def test_workers_share_wiring(self, factory):
        m = factory.create_queue_manager("w2", start_background=False)
        [w] = factory.create_workers(
            "w2", 1, lambda ctx, msg: (_ for _ in ()).throw(RuntimeError("x")),
            start=False)
        msg = Message(max_retries=0)
        m.push_message(msg)
        w.process_batch()
        assert factory.get_dead_letter_queue("w2").size() == 1

    def test_unknown_manager_raises(self, factory):
        with pytest.raises(KeyError):
            factory.create_workers("missing", 1, lambda ctx, m: None)

    def test_stop_all(self, fake_clock, queue_backend):
        f = QueueFactory(clock=fake_clock, backend=queue_backend)
        f.create_queue_manager("x", start_background=False)
        ws = f.create_workers("x", 2, lambda ctx, m: None, start=True)
        assert all(w.running for w in ws)
        f.stop_all()
        assert all(not w.running for w in ws)
