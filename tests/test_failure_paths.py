"""Regression tests for message-loss / accounting-leak paths found in
review: delayed-queue redelivery, DLQ requeue atomicity, stale-expiry
accounting, try_pop error transparency, peek/push race safety."""

import pytest

from llmq_tpu.core.config import default_config
from llmq_tpu.core.errors import QueueFullError, QueueNotFoundError
from llmq_tpu.core.types import Message, MessageStatus
from llmq_tpu.queueing.dead_letter_queue import DeadLetterQueue
from llmq_tpu.queueing.delayed_queue import DelayedQueue
from llmq_tpu.queueing.factory import QueueFactory
from llmq_tpu.queueing.queue_manager import QueueManager


class TestDelayedRedelivery:
    def test_failed_delivery_is_rescheduled_not_lost(self, fake_clock):
        attempts = []

        def deliver(q, m):
            attempts.append(fake_clock.now())
            if len(attempts) < 3:
                raise QueueFullError(q, 1)

        dq = DelayedQueue(deliver, clock=fake_clock)
        dq.schedule_after(Message(content="x"), 1.0, "normal")
        fake_clock.advance(1.01)
        dq.run_due_once()
        assert len(attempts) == 1
        assert dq.size() == 1  # re-scheduled, not lost
        fake_clock.advance(DelayedQueue.REDELIVERY_DELAY + 0.01)
        dq.run_due_once()
        fake_clock.advance(DelayedQueue.REDELIVERY_DELAY + 0.01)
        dq.run_due_once()
        assert len(attempts) == 3
        assert dq.size() == 0  # finally delivered

    def test_exhausted_redelivery_goes_to_on_drop(self, fake_clock):
        dropped = []

        def deliver(q, m):
            raise QueueNotFoundError(q)

        dq = DelayedQueue(deliver, clock=fake_clock,
                          on_drop=lambda q, m, r: dropped.append((q, m, r)))
        dq.schedule_after(Message(content="doomed"), 0.5, "gone")
        for _ in range(DelayedQueue.MAX_DELIVERY_ATTEMPTS + 1):
            fake_clock.advance(DelayedQueue.REDELIVERY_DELAY + 0.01)
            dq.run_due_once()
        assert len(dropped) == 1
        assert dropped[0][0] == "gone"
        assert dq.size() == 0

    def test_factory_routes_undeliverable_to_dlq(self, fake_clock, queue_backend):
        f = QueueFactory(clock=fake_clock, backend=queue_backend)
        f.create_queue_manager("m", start_background=False)
        dq = f.get_delayed_queue("m")
        dlq = f.get_dead_letter_queue("m")
        m = Message()
        dq.schedule_after(m, 0.5, "no_such_queue")
        for _ in range(DelayedQueue.MAX_DELIVERY_ATTEMPTS + 1):
            fake_clock.advance(DelayedQueue.REDELIVERY_DELAY + 0.01)
            dq.run_due_once()
        assert dlq.size() == 1
        assert dlq.get(m.id).fail_reason.startswith("undeliverable")
        f.stop_all()


class TestDLQRequeueAtomicity:
    def test_failed_requeue_restores_item(self, fake_clock, queue_backend):
        cfg = default_config()
        cfg.queue.max_queue_size = 1
        qm = QueueManager("t", config=cfg, clock=fake_clock,
                          backend=queue_backend, enable_metrics=False)
        qm.push_message(Message())  # fill the normal queue (capacity 1)
        dlq = DeadLetterQueue(clock=fake_clock)
        dead = Message(content="dead")
        dead.status = MessageStatus.FAILED
        dead.retry_count = 3
        dlq.push(dead, "boom", "normal")
        with pytest.raises(QueueFullError):
            dlq.requeue(dead.id, qm)
        # Item restored with its original state — in exactly one place.
        assert dlq.size() == 1
        restored = dlq.get(dead.id).message
        assert restored.status == MessageStatus.FAILED
        assert restored.retry_count == 3

    def test_batch_requeue_continues_past_full_queue(self, fake_clock, queue_backend):
        cfg = default_config()
        cfg.queue.max_queue_size = 1
        qm = QueueManager("t", config=cfg, clock=fake_clock,
                          backend=queue_backend, enable_metrics=False)
        dlq = DeadLetterQueue(clock=fake_clock)
        a = Message(content="a")
        b = Message(content="b")
        dlq.push(a, "r", "normal")
        dlq.push(b, "r", "low")
        qm.push_message(Message())  # normal is now full
        out = dlq.batch_requeue(qm)
        # b made it (low queue has room), a stayed in the DLQ.
        assert [m.content for m in out] == ["b"]
        assert dlq.size() == 1
        assert dlq.get(a.id)


class TestStaleExpiryAccounting:
    def test_inflight_map_does_not_leak(self, fake_clock, queue_backend):
        cfg = default_config()
        cfg.queue.stale_message_age = 10.0
        cfg.scheduler.scale_down_threshold = -1
        qm = QueueManager("t", config=cfg, clock=fake_clock,
                          backend=queue_backend, enable_metrics=False)
        for _ in range(5):
            qm.push_message(Message())
        assert len(qm._inflight) == 5
        fake_clock.advance(60.0)
        qm.run_monitor_once()
        assert len(qm._inflight) == 0


class TestTryPopTransparency:
    def test_unknown_queue_raises_not_none(self, fake_clock, queue_backend):
        qm = QueueManager("t", clock=fake_clock, backend=queue_backend,
                          enable_metrics=False)
        with pytest.raises(QueueNotFoundError):
            qm.try_pop_message("typo_queue")
        assert qm.try_pop_message("normal") is None  # empty → None


class TestWorkerBackoffAlwaysReal:
    def test_worker_without_explicit_delayed_queue_honors_backoff(
            self, fake_clock, queue_backend):
        # Review finding: bare Worker used to re-push instantly, burning
        # all retries in milliseconds.
        from llmq_tpu.queueing.worker import Worker

        attempts = []

        def flaky(ctx, m):
            attempts.append(fake_clock.now())
            raise RuntimeError("down")

        qm = QueueManager("bare", clock=fake_clock, enable_metrics=False,
                          backend=queue_backend)
        w = Worker("w", qm, flaky, clock=fake_clock)  # no delayed_queue arg
        qm.push_message(Message(max_retries=2))
        w.process_batch()
        assert len(attempts) == 1
        # Immediately re-running must NOT retry (backoff not elapsed).
        w.process_batch()
        assert len(attempts) == 1
        fake_clock.advance(1.01)
        w.process_batch()  # owned delayed queue ticked synchronously
        assert len(attempts) == 2


class TestEnvValidation:
    def test_env_override_rejects_bad_strategy(self, monkeypatch):
        from llmq_tpu.core.config import load_config

        monkeypatch.setenv("LLMQ_LOADBALANCER_STRATEGY", "fastest")
        with pytest.raises(ValueError):
            load_config()


class TestAffinitySaturation:
    def test_sticky_session_respects_max_connections(self, fake_clock):
        from llmq_tpu.core.config import LoadBalancerConfig
        from llmq_tpu.core.errors import NoEndpointError
        from llmq_tpu.loadbalancer import Endpoint, LoadBalancer

        lb = LoadBalancer(LoadBalancerConfig(health_check_interval=0),
                          clock=fake_clock)
        lb.add_endpoint(Endpoint(id="e0", max_connections=1))
        lb.add_endpoint(Endpoint(id="e1", max_connections=1))
        first = lb.get_endpoint(session_id="s").id
        # Pinned endpoint saturated → affinity must not oversubscribe it.
        second = lb.get_endpoint(session_id="s").id
        assert second != first
        with pytest.raises(NoEndpointError):
            lb.get_endpoint(session_id="s")


class TestAllocationTTLIndependentOfPendingTimeout:
    def test_short_pending_timeout_does_not_shorten_allocation(self, fake_clock):
        from llmq_tpu.core.config import ResourceSchedulerConfig
        from llmq_tpu.scheduling import (
            Resource, ResourceRequest, ResourceScheduler, ResourceType)

        cfg = ResourceSchedulerConfig(allocation_timeout=300.0)
        rs = ResourceScheduler(cfg, clock=fake_clock)
        rs.register_resource(Resource(
            id="r0", capabilities={"tpu"},
            capacity={ResourceType.CHIP: 8.0}))
        req = ResourceRequest(capabilities={"tpu"},
                              amounts={ResourceType.CHIP: 4.0}, timeout=5.0)
        alloc = rs.request_resource_now(req)
        rs.heartbeat("r0")
        fake_clock.advance(10.0)  # > pending timeout, < allocation TTL
        rs.heartbeat("r0")
        out = rs.run_monitor_once()
        assert out["expired_allocations"] == 0
        assert rs.get_allocation(alloc.id) is not None
