"""Tiered KV plane (llmq_tpu/tiering/, docs/tiering.md): HBM →
host-DRAM → store hierarchy under the prefix cache and conversation
pins — host-pool/codec units, the plane's demote/promote/spill/
recompute state machine, the prefix-cache demotion seam, the sqlite
spill-store hardening, prefix-handle tier semantics, engine
integration on echo AND CPU-mode JAX (token-for-token equivalence per
tier, off-switch byte-equivalence), async-pipeline interplay, usage
billing at demotion, and the new metric families."""

import threading
import time

import jax
import numpy as np
import pytest

from llmq_tpu.core.clock import FakeClock
from llmq_tpu.core.config import (ConversationConfig, KVTieringConfig,
                                  PrefixCacheConfig)
from llmq_tpu.conversation.persistence import InMemoryStore, SqliteStore
from llmq_tpu.conversation.state_manager import StateManager
from llmq_tpu.engine.engine import GenRequest, InferenceEngine
from llmq_tpu.engine.executor import EchoExecutor, JaxExecutor
from llmq_tpu.engine.kv_allocator import PageAllocator
from llmq_tpu.engine.tokenizer import ByteTokenizer
from llmq_tpu.observability.usage import get_usage_ledger
from llmq_tpu.prefixcache import PrefixCache
from llmq_tpu.tiering import (HostTierPool, KVTieringPlane, decode_blob,
                              encode_blob, pack_pages,
                              page_payload_nbytes, unpack_pages)


@pytest.fixture(autouse=True)
def _usage_off():
    led = get_usage_ledger()
    led.reconfigure(enabled=False)
    led.clear()
    yield
    led.reconfigure(enabled=False)
    led.clear()


def wait_until(fn, timeout=5.0, step=0.002):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


# -- host pool -----------------------------------------------------------------


class TestHostTierPool:
    def test_take_give_lifecycle(self):
        pool = HostTierPool(capacity_bytes=1024, page_nbytes=256)
        assert pool.total_buffers == 4
        bufs = pool.take(3)
        assert bufs is not None and len(bufs) == 3
        assert pool.free_buffers() == 1
        assert pool.used_bytes() == 3 * 256
        pool.give(bufs)
        assert pool.free_buffers() == 4

    def test_all_or_nothing(self):
        pool = HostTierPool(1024, 256)
        held = pool.take(3)
        assert pool.take(2) is None          # only 1 left
        assert pool.free_buffers() == 1      # nothing partially taken
        pool.give(held)

    def test_double_give_is_noop(self):
        pool = HostTierPool(512, 256)
        bufs = pool.take(1)
        pool.give(bufs)
        pool.give(bufs)                      # second give ignored
        assert pool.free_buffers() == 2
        # The freed slot can be handed out again exactly once.
        a = pool.take(2)
        assert a is not None and pool.take(1) is None
        pool.give(a)

    def test_foreign_arrays_ignored(self):
        pool = HostTierPool(512, 256)
        pool.give([np.zeros(256, np.uint8)])
        assert pool.free_buffers() == 2

    def test_buffers_are_arena_views(self):
        pool = HostTierPool(1024, 128)
        bufs = pool.take(2)
        for b in bufs:
            assert b.base is pool._arena     # one allocation total
        pool.give(bufs)

    def test_zero_page_bytes(self):
        pool = HostTierPool(1 << 20, 0)      # content-free backend
        assert pool.total_buffers == 0 and pool.total_bytes == 0


# -- codec ---------------------------------------------------------------------


def _leaves(n_pages, seed=0):
    """Per-leaf page gathers shaped like a tiny int8-KV cache tree:
    (L, N, page, flat-heads) values + (L, N, heads, page) scales."""
    rng = np.random.default_rng(seed)
    import ml_dtypes

    return [
        rng.integers(-100, 100, (2, n_pages, 8, 16)).astype(np.int8),
        rng.standard_normal((2, n_pages, 2, 8)).astype(
            ml_dtypes.bfloat16),
        rng.standard_normal((2, n_pages, 8, 16)).astype(np.float32),
    ]


def _specs(leaves):
    return [((l.shape[0],) + l.shape[2:], np.dtype(l.dtype))
            for l in leaves]


class TestCodec:
    def test_pack_unpack_roundtrip(self):
        leaves = _leaves(3)
        specs = _specs(leaves)
        per = page_payload_nbytes(specs)
        bufs = [np.empty(per, np.uint8) for _ in range(3)]
        pack_pages(leaves, bufs)
        out = unpack_pages(bufs, specs)
        for a, b in zip(leaves, out):
            assert a.dtype == b.dtype and a.shape == b.shape
            assert np.array_equal(np.asarray(a, np.uint8).view(np.uint8)
                                  if False else a.view(np.uint8),
                                  b.view(np.uint8))

    def test_blob_roundtrip(self):
        leaves = _leaves(2, seed=7)
        specs = _specs(leaves)
        per = page_payload_nbytes(specs)
        bufs = [np.empty(per, np.uint8) for _ in range(2)]
        pack_pages(leaves, bufs)
        blob = encode_blob(bufs, specs)
        bufs2, specs2 = decode_blob(blob)
        assert [tuple(s) for s, _ in specs2] == [tuple(s)
                                                 for s, _ in specs]
        for a, b in zip(bufs, bufs2):
            assert np.array_equal(a, b)

    def test_corrupt_blob_raises(self):
        leaves = _leaves(1)
        specs = _specs(leaves)
        per = page_payload_nbytes(specs)
        bufs = [np.empty(per, np.uint8)]
        pack_pages(leaves, bufs)
        blob = encode_blob(bufs, specs)
        with pytest.raises(ValueError):
            decode_blob(b"garbage" + blob)
        with pytest.raises(ValueError):
            decode_blob(blob[:-10])          # truncated payload


# -- plane state machine (fake executor) ---------------------------------------


class FakeKVExec:
    """Numpy-backed 'device': deterministic payload per page id so the
    tests can assert content fidelity end to end."""

    def __init__(self):
        self.injected = {}

    def kv_page_spec(self):
        return [((2, 4, 8), np.dtype(np.float32))]

    def export_kv_pages(self, pages):
        out = np.stack(
            [np.full((2, 4, 8), float(p), np.float32) for p in pages],
            axis=1)
        return [out]

    def import_kv_pages(self, pages, leaves):
        for i, p in enumerate(pages):
            self.injected[p] = np.asarray(leaves[0][:, i]).copy()


def mk_plane(cfg=None, execu=None, clock=None, store=None):
    plane = KVTieringPlane(cfg or KVTieringConfig(enabled=True),
                           "test", execu or FakeKVExec(), clock=clock)
    if store is not None:
        plane.store = store
    return plane


class TestPlaneStateMachine:
    def test_demote_then_host_claim(self):
        plane = mk_plane()
        plane.demote("c", [3, 5], list(range(16)), 16, None)
        assert wait_until(lambda: plane.counts()["host"] == 1)
        status, entry = plane.claim("c")
        assert status == "ready" and entry.tier == "host"
        leaves = plane.unpack(entry)
        # Content fidelity: page 3's payload is all-3.0, page 5 all-5.0.
        assert np.all(np.asarray(leaves[0][:, 0]) == 3.0)
        assert np.all(np.asarray(leaves[0][:, 1]) == 5.0)
        plane.release(entry)
        assert plane.pool.free_buffers() == plane.pool.total_buffers
        assert plane.claim("c") == ("none", None)
        plane.stop()

    def test_spill_to_store_and_load_back(self):
        plane = mk_plane(KVTieringConfig(enabled=True, host_capacity_mb=0),
                         store=InMemoryStore())
        plane.demote("c", [7], list(range(8)), 8, 42)
        assert wait_until(lambda: plane.counts()["store"] == 1)
        assert plane.stats()["spills"] == 1
        assert plane.prepare("c")            # kicks the load
        status = "wait"
        for _ in range(500):
            status, entry = plane.claim("c")
            if status == "ready":
                break
            time.sleep(0.002)
        assert status == "ready"
        assert entry.source_tier == "store"
        assert entry.pending == 42
        leaves = plane.unpack(entry)
        assert np.all(np.asarray(leaves[0][:, 0]) == 7.0)
        plane.release(entry)
        plane.stop()

    def test_claim_triggers_load_without_prepare(self):
        plane = mk_plane(KVTieringConfig(enabled=True, host_capacity_mb=0),
                         store=InMemoryStore())
        plane.demote("c", [2], list(range(8)), 8, None)
        assert wait_until(lambda: plane.counts()["store"] == 1)
        status = "wait"
        for _ in range(500):
            status, entry = plane.claim("c")
            if status == "ready":
                break
            time.sleep(0.002)
        assert status == "ready" and entry.payload is not None
        plane.release(entry)
        plane.stop()

    def test_no_store_degrades_to_recompute(self):
        plane = mk_plane(KVTieringConfig(enabled=True, host_capacity_mb=0,
                                         store_spill=False))
        plane.demote("c", [2], [1, 2, 3], 3, None)
        assert wait_until(lambda: plane.counts()["recompute"] == 1)
        status, entry = plane.claim("c")
        assert status == "ready" and entry.payload is None
        assert entry.tokens == [1, 2, 3]
        plane.release(entry)
        plane.stop()

    def test_promote_timeout_falls_back_to_recompute(self):
        plane = mk_plane(KVTieringConfig(enabled=True,
                                         promote_timeout_s=0.02))
        # An entry that never becomes ready (no worker ran: inject one
        # manually in the not-ready state).
        from llmq_tpu.tiering.plane import TierEntry
        entry = TierEntry("c", [1, 2], 2, None, 1, 0.0)
        with plane._mu:
            plane._entries["c"] = entry
        assert plane.claim("c")[0] == "wait"
        time.sleep(0.03)
        status, got = plane.claim("c")
        assert status == "ready" and got.payload is None
        assert got.tokens == [1, 2]          # recompute still exact
        plane.stop()

    def test_forget_drops_all_tiers(self):
        store = InMemoryStore()
        plane = mk_plane(KVTieringConfig(enabled=True, host_capacity_mb=0),
                         store=store)
        plane.demote("c", [4], list(range(8)), 8, None)
        assert wait_until(lambda: store.load_kv("c") is not None)
        plane.forget("c")
        assert wait_until(lambda: store.load_kv("c") is None)
        assert plane.claim("c") == ("none", None)
        plane.stop()

    def test_restash_puts_entry_back(self):
        plane = mk_plane()
        plane.demote("c", [3], list(range(8)), 8, None)
        assert wait_until(lambda: plane.counts()["host"] == 1)
        status, entry = plane.claim("c")
        assert status == "ready"
        plane.restash("c", entry)
        status2, entry2 = plane.claim("c")
        assert status2 == "ready" and entry2 is entry
        plane.release(entry2)
        plane.stop()

    def test_host_bound_spills_coldest(self):
        clock = FakeClock()
        plane = mk_plane(KVTieringConfig(enabled=True,
                                         host_max_conversations=2),
                         clock=clock, store=InMemoryStore())
        for i in range(3):
            plane.demote(f"c{i}", [i + 1], list(range(8)), 8, None)
            # Wait for the extract itself (counts alone flip at demote
            # time): spill victims must be READY residents.
            assert wait_until(
                lambda i=i: plane._entries[f"c{i}"].ready.is_set()
                or plane._entries[f"c{i}"].spilling)
            clock.advance(1.0)
        assert wait_until(lambda: plane.counts()["store"] == 1
                          and plane.counts()["host"] == 2)
        # The coldest (first-demoted) conversation is the spilled one.
        with plane._mu:
            assert plane._entries["c0"].tier == "store"
        plane.stop()

    def test_round_trip_counted_inside_window(self):
        clock = FakeClock()
        plane = mk_plane(clock=clock)
        plane.demote("c", [3], list(range(8)), 8, None)
        assert wait_until(lambda: plane.counts()["host"] == 1)
        status, entry = plane.claim("c")
        plane.note_promoted(entry, "host", 0.1)
        plane.release(entry)
        assert plane.stats()["round_trips"] == 1
        # Outside the window: no thrash.
        plane.demote("c", [4], list(range(8)), 8, None)
        assert wait_until(lambda: plane.counts()["host"] == 1)
        clock.advance(3600.0)
        status, entry = plane.claim("c")
        plane.note_promoted(entry, "host", 0.1)
        plane.release(entry)
        assert plane.stats()["round_trips"] == 1
        plane.stop()

    def test_timeout_claim_racing_spill_leaks_no_buffers(self):
        """A promote-timeout claim racing a QUEUED spill must not leak
        host-pool buffers: the spill job owns its buffers exclusively
        (popped at claim-for-spill) and returns them itself even when
        the entry was abandoned mid-flight."""
        gate = threading.Event()

        class SlowStore(InMemoryStore):
            def save_kv(self, cid, blob):
                gate.wait(5.0)
                super().save_kv(cid, blob)

        # Pool holds exactly one conversation; host bound of 1 entry.
        spec_bytes = page_payload_nbytes(FakeKVExec().kv_page_spec())
        cfg = KVTieringConfig(enabled=True, host_max_conversations=1,
                              promote_timeout_s=0.01)
        cfg.host_capacity_mb = 0        # replaced below with raw bytes
        plane = KVTieringPlane(cfg, "leak", FakeKVExec())
        plane.pool = HostTierPool(2 * spec_bytes, spec_bytes)
        plane.store = SlowStore()
        plane.demote("c0", [1], list(range(8)), 8, None)
        assert wait_until(lambda: plane.counts()["host"] == 1)
        # Second demote pushes past the bound → spill of c0 queued,
        # blocked inside save_kv by the gate.
        plane.demote("c1", [2], list(range(8)), 8, None)
        assert wait_until(
            lambda: plane._entries["c0"].spilling
            or plane._entries["c0"].tier == "store")
        # Claim c0 while its spill is stuck → promote timeout →
        # recompute fallback.
        deadline = time.perf_counter() + 2.0
        status = "wait"
        while time.perf_counter() < deadline:
            status, entry = plane.claim("c0")
            if status != "wait":
                break
            time.sleep(0.005)
        assert status == "ready" and entry.payload is None
        plane.release(entry)
        gate.set()                       # spill completes late
        assert wait_until(
            lambda: plane.pool.free_buffers() + 1
            == plane.pool.total_buffers)  # only c1's entry holds one
        plane.stop()

    def test_wait_since_resets_on_publish_and_restash(self):
        plane = mk_plane()
        gate = threading.Event()
        plane._submit(lambda: gate.wait(5.0))   # park the worker
        plane.demote("c", [3], list(range(8)), 8, None)
        # Claim while the extract is parked: starts the timeout epoch.
        assert plane.claim("c")[0] == "wait"
        with plane._mu:
            entry = plane._entries["c"]
        assert entry.wait_since is not None
        gate.set()
        assert wait_until(lambda: entry.ready.is_set())
        # Publication resets the epoch (a LATER wait gets the full
        # timeout, instead of inheriting this one's elapsed part).
        assert entry.wait_since is None
        status, got = plane.claim("c")
        assert status == "ready"
        got.wait_since = 123.0
        plane.restash("c", got)
        assert got.wait_since is None
        plane.stop()

    def test_async_degradation_fires_tier_change(self):
        """A worker-side degradation (spill fails, no payload
        preserved) downgrades the prefix handle through the
        on_tier_change callback — prefill_estimate must not keep
        promising a prefix nothing can serve."""

        class BrokenStore(InMemoryStore):
            def save_kv(self, cid, blob):
                raise RuntimeError("store down")

        changes = []
        plane = mk_plane(KVTieringConfig(enabled=True,
                                         host_capacity_mb=0),
                         store=BrokenStore())
        plane.on_tier_change = lambda cid, tier: changes.append(
            (cid, tier))
        plane.demote("c", [2], list(range(8)), 8, None)
        assert wait_until(lambda: plane.counts()["recompute"] == 1)
        assert ("c", "dropped") in changes
        plane.stop()

    def test_content_free_metadata_entry(self):
        class Echoish:
            kv_content_free = True

        plane = mk_plane(execu=Echoish())
        plane.demote("c", [1, 2], [9, 8, 7], 3, None)
        status, entry = plane.claim("c")     # ready immediately
        assert status == "ready"
        assert entry.tier == "host" and entry.payload is None
        assert plane.content_free
        plane.release(entry)
        plane.stop()


# -- prefix-cache demotion seam (satellite, standalone) ------------------------


class TestPrefixCacheDemotionSeam:
    def _cache(self, pages=32, page_size=4):
        alloc = PageAllocator(pages, page_size)
        return alloc, PrefixCache(alloc, page_size)

    def test_default_is_plain_free(self):
        alloc, pc = self._cache()
        pages = alloc.alloc(2)
        ids = list(range(8))
        pc.insert(ids, pages)
        alloc.free(pages)                    # caller's refs
        freed = pc.evict_pages(2)
        assert freed == 2
        assert alloc.available() == alloc.total

    def test_callback_sees_token_path_and_page(self):
        alloc, pc = self._cache()
        pages = alloc.alloc(3)
        ids = list(range(12))
        pc.insert(ids, pages)
        alloc.free(pages)
        seen = []
        pc.set_demotion_callback(lambda path, page: seen.append(
            (list(path), page)))
        assert pc.evict_pages(3) == 3
        # Leaves evict bottom-up: the deepest block first, each with
        # its FULL root→node token path.
        paths = sorted(seen, key=lambda s: len(s[0]))
        assert [p for p, _ in paths] == [ids[:4], ids[:8], ids[:12]]
        assert {pg for _, pg in seen} == set(pages)

    def test_callback_skipped_for_shared_pages(self):
        alloc, pc = self._cache()
        pages = alloc.alloc(1)
        ids = list(range(4))
        pc.insert(ids, pages)                # tree retains; we hold too
        seen = []
        pc.set_demotion_callback(lambda path, page: seen.append(page))
        # Tree eviction under max_pages pressure takes ANY zero-lock
        # leaf; the page is still shared with us → no demotion signal.
        assert pc._evict_locked(target_nodes=1) == 0   # not last holder
        assert seen == []
        alloc.free(pages)

    def test_invalidate_never_fires_callback(self):
        """Delete contract: invalidated content must not be captured
        into a lower tier."""
        alloc, pc = self._cache()
        pages = alloc.alloc(2)
        ids = list(range(8))
        pc.insert(ids, pages)
        alloc.free(pages)
        seen = []
        pc.set_demotion_callback(lambda path, page: seen.append(page))
        assert pc.invalidate(ids) == 2
        assert seen == []

    def test_callback_failure_does_not_break_eviction(self):
        alloc, pc = self._cache()
        pages = alloc.alloc(2)
        pc.insert(list(range(8)), pages)
        alloc.free(pages)

        def boom(path, page):
            raise RuntimeError("demoter broke")

        pc.set_demotion_callback(boom)
        assert pc.evict_pages(2) == 2
        assert alloc.available() == alloc.total


# -- sqlite spill store hardening (satellite) ----------------------------------


class TestSqliteSpillStore:
    def test_kv_blob_roundtrip(self, tmp_path):
        store = SqliteStore(str(tmp_path / "kv.db"))
        blob = bytes(range(256)) * 17        # binary, not utf-8 safe
        store.save_kv("c1", blob)
        assert store.load_kv("c1") == blob
        store.save_kv("c1", b"v2")           # upsert
        assert store.load_kv("c1") == b"v2"
        store.delete_kv("c1")
        assert store.load_kv("c1") is None
        store.close()

    def test_migration_on_pre_tiering_db(self, tmp_path):
        """An existing database without kv_payloads upgrades in place
        on open (idempotent CREATE IF NOT EXISTS migration)."""
        import sqlite3

        path = str(tmp_path / "old.db")
        conn = sqlite3.connect(path)
        conn.execute(
            """CREATE TABLE conversations (
                id TEXT PRIMARY KEY, user_id TEXT NOT NULL,
                state TEXT NOT NULL, context TEXT NOT NULL DEFAULT '',
                messages TEXT NOT NULL DEFAULT '[]',
                metadata TEXT NOT NULL DEFAULT '{}',
                created_at REAL NOT NULL, updated_at REAL NOT NULL,
                last_active_at REAL NOT NULL)""")
        conn.commit()
        conn.close()
        store = SqliteStore(path)
        store.save_kv("c", b"payload")
        assert store.load_kv("c") == b"payload"
        store.close()

    def test_busy_timeout_and_wal_set(self, tmp_path):
        store = SqliteStore(str(tmp_path / "t.db"))
        conn = store._conn()
        assert conn.execute("PRAGMA busy_timeout").fetchone()[0] == 10000
        assert conn.execute(
            "PRAGMA journal_mode").fetchone()[0].lower() == "wal"
        store.close()

    def test_concurrent_save_load_never_locks(self, tmp_path):
        """The spill tier's contract: 4 threads hammering save/load/
        delete concurrently never raise 'database is locked' (WAL +
        busy_timeout)."""
        from llmq_tpu.core.types import Conversation

        store = SqliteStore(str(tmp_path / "conc.db"))
        errors = []
        stop = threading.Event()

        def worker(wid):
            try:
                for i in range(120):
                    cid = f"c{wid}-{i % 7}"
                    store.save_kv(cid, bytes([wid]) * 2048)
                    store.load_kv(cid)
                    conv = Conversation(
                        id=cid, user_id=f"u{wid}", created_at=1.0,
                        updated_at=1.0, last_active_at=1.0)
                    store.save(conv)
                    store.load(cid)
                    if i % 11 == 0:
                        store.delete_kv(cid)
                    if stop.is_set():
                        return
            except Exception as e:  # noqa: BLE001 — collected for assert
                errors.append(e)
                stop.set()

        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        store.close()


# -- prefix-handle tier semantics (satellite) ----------------------------------


def mk_echo_engine(tiering=None, pin_ttl=600.0, clock=None, pages=128,
                   metrics=False, **kw):
    tok = ByteTokenizer()
    ex = EchoExecutor(batch_size=4, page_size=8, num_pages=pages,
                      max_pages_per_seq=16, eos_id=tok.eos_id,
                      chunk_size=4, **kw)
    return InferenceEngine(ex, tok, enable_metrics=metrics,
                           name="tiertest", kv_pin_ttl=pin_ttl,
                           clock=clock, kv_tiering=tiering,
                           prefix_cache=PrefixCacheConfig(enabled=True))


def run_turn(eng, rid, prompt, conv, tokens=8):
    h = eng.submit(GenRequest(id=rid, prompt=prompt,
                              conversation_id=conv,
                              max_new_tokens=tokens))
    eng.run_until_idle()
    assert h.result is not None and h.result.finish_reason in (
        "eos", "length")
    return h


class TestPrefixHandleTier:
    def test_handle_outlives_residency_estimate_per_tier(self):
        """The record_prefix_handle docstring promise, pinned: after
        the pin is reclaimed the handle survives — and its tier field
        decides the prefill estimate. Demoted (tiering on) → still
        cached (promotable); tiering off with the radix tree ALSO
        emptied → dropped → a correct non-cached estimate."""
        clock = FakeClock()
        for tiering, expect_cached in ((KVTieringConfig(enabled=True),
                                        True), (None, False)):
            eng = mk_echo_engine(tiering=tiering, pin_ttl=5.0,
                                 clock=clock)
            sm = StateManager(ConversationConfig(), clock=clock)
            eng.attach_conversation_manager(sm)
            sm.get_or_create("c", "u")
            run_turn(eng, "t1", "hello world conversation", "c")
            h = sm.prefix_handle("c")
            assert h is not None and h["tier"] == "hbm"
            cached0, _ = eng.prefill_estimate("c", 10)
            assert cached0 > 0               # pin resident
            if tiering is None:
                # Radix loses the blocks too (LRU pressure analogue):
                # the reclaim below must then mark the handle dropped.
                eng._prefix_cache.invalidate_all()
            clock.advance(6.0)
            eng.step()                       # TTL reclaim
            assert "c" not in eng.cached_conversations()
            h = sm.prefix_handle("c")
            assert h is not None             # handle OUTLIVES the pin
            assert h["tier"] == ("host" if tiering else "dropped")
            cached, new = eng.prefill_estimate("c", 10)
            if expect_cached:
                assert cached > 0            # promotable from host
            else:
                assert cached == 0           # gone for good: all-new
            assert new == 10
            eng.stop()
            sm.stop()

    def test_estimate_stays_optimistic_with_radix_fallback(self):
        """Tiering off, pin reclaimed, radix still holding the blocks:
        the handle stays promotable and the estimate stays cached —
        exactly the pre-tiering behavior (turn N+1 adopts the tree)."""
        clock = FakeClock()
        eng = mk_echo_engine(tiering=None, pin_ttl=5.0, clock=clock)
        sm = StateManager(ConversationConfig(), clock=clock)
        eng.attach_conversation_manager(sm)
        sm.get_or_create("c", "u")
        run_turn(eng, "t1", "hello world conversation", "c")
        clock.advance(6.0)
        eng.step()
        assert sm.prefix_handle("c")["tier"] == "hbm"
        cached, _ = eng.prefill_estimate("c", 10)
        assert cached > 0
        eng.stop()
        sm.stop()

    def test_promotion_moves_handle_back_to_hbm(self):
        clock = FakeClock()
        eng = mk_echo_engine(tiering=KVTieringConfig(enabled=True),
                             pin_ttl=5.0, clock=clock)
        sm = StateManager(ConversationConfig(), clock=clock)
        eng.attach_conversation_manager(sm)
        sm.get_or_create("c", "u")
        run_turn(eng, "t1", "hello world", "c")
        clock.advance(6.0)
        eng.step()
        assert sm.prefix_handle("c")["tier"] == "host"
        run_turn(eng, "t2", " again", "c")
        # Promotion re-pinned, then the finish re-recorded the handle.
        assert sm.prefix_handle("c")["tier"] == "hbm"
        eng.stop()
        sm.stop()

    def test_update_prefix_handle_tier_contract(self):
        sm = StateManager(ConversationConfig())
        assert not sm.update_prefix_handle_tier("nope", "host")
        sm.get_or_create("c", "u")
        assert not sm.update_prefix_handle_tier("c", "host")  # no handle
        sm.record_prefix_handle("c", {"length": 32, "pages": 4,
                                      "tier": "hbm"})
        assert sm.update_prefix_handle_tier("c", "store")
        assert sm.prefix_handle("c")["tier"] == "store"
        assert sm.prefix_handle("c")["length"] == 32   # rest untouched

    def test_unpin_after_demotion_bills_tenant(self):
        """Economics seam: the HBM pin's page-second meter closes AT
        DEMOTION (host residency is not the priced HBM resource), and
        the accrued page-seconds land on the pinning tenant."""
        led = get_usage_ledger()
        led.reconfigure(enabled=True)
        led.clear()
        eng = mk_echo_engine(tiering=KVTieringConfig(enabled=True),
                             pin_ttl=0.05)
        h = eng.submit(GenRequest(id="t1", prompt="hello world billing",
                                  conversation_id="c", max_new_tokens=8,
                                  tenant_id="acme"))
        eng.run_until_idle()
        assert h.result.finish_reason in ("eos", "length")
        time.sleep(0.08)                     # real time: the tracker
        eng.step()                           # integrates wall-clock
        assert "c" not in eng.cached_conversations()
        snap = led.snapshot()
        assert snap["totals"]["pinned_kv_page_seconds"] > 0
        assert snap["tenants"]["acme"]["kv_page_seconds"] > 0
        eng.stop()


# -- echo engine integration ---------------------------------------------------


class TestEchoEngineTiering:
    def test_off_switch_builds_nothing(self):
        eng = mk_echo_engine(tiering=KVTieringConfig(enabled=False))
        assert eng._tiering is None
        assert "kv_tiering" not in eng.get_stats()
        eng.stop()

    def test_demote_promote_equivalence_vs_resident_pin(self):
        """Token-for-token: tiering ON with the pin expired between
        turns produces the same streams as the pin never expiring."""
        clock_a, clock_b = FakeClock(), FakeClock()
        eng_a = mk_echo_engine(pin_ttl=600.0, clock=clock_a)   # resident
        eng_b = mk_echo_engine(tiering=KVTieringConfig(enabled=True),
                               pin_ttl=5.0, clock=clock_b)
        outs = []
        for eng, clock in ((eng_a, clock_a), (eng_b, clock_b)):
            h1 = run_turn(eng, "t1", "the quick brown fox", "c")
            clock.advance(6.0)
            eng.step()
            h2 = run_turn(eng, "t2", " jumps over the dog", "c")
            outs.append((h1.result.tokens, h2.result.tokens,
                         h2.result.cached_tokens))
        assert outs[0][0] == outs[1][0]
        assert outs[0][1] == outs[1][1]
        assert outs[1][2] > 0                # promotion actually served
        st = eng_b.get_stats()["kv_tiering"]
        assert st["hits"]["host"] == 1 and st["demotions"] == 1
        assert "c" not in eng_a.cached_conversations() or True
        eng_a.stop()
        eng_b.stop()

    def test_pool_pressure_demotes_instead_of_killing(self):
        """A new admission that pressure-reclaims an idle pinned
        conversation demotes it — the later re-arrival is a host hit,
        not a recompute."""
        eng = mk_echo_engine(tiering=KVTieringConfig(enabled=True),
                             pages=17)       # 16 allocatable pages
        run_turn(eng, "t1", "x" * 40, "alpha", tokens=4)
        assert "alpha" in eng.cached_conversations()
        # A fat single-shot request forces pool pressure.
        run_turn(eng, "big", "y" * 100, "", tokens=4)
        assert "alpha" not in eng.cached_conversations()
        st = eng.get_stats()["kv_tiering"]
        assert st["demotions"] == 1
        h = run_turn(eng, "t2", "more text", "alpha", tokens=4)
        assert h.result.cached_tokens > 0
        assert eng.get_stats()["kv_tiering"]["hits"]["host"] == 1
        eng.stop()

    def test_delete_forgets_all_tiers(self):
        clock = FakeClock()
        eng = mk_echo_engine(tiering=KVTieringConfig(enabled=True),
                             pin_ttl=5.0, clock=clock)
        sm = StateManager(ConversationConfig(), clock=clock)
        eng.attach_conversation_manager(sm)
        sm.get_or_create("c", "u")
        run_turn(eng, "t1", "private content", "c")
        clock.advance(6.0)
        eng.step()                           # demoted to host tier
        assert eng.get_stats()["kv_tiering"]["entries"] == 1
        sm.delete("c")                       # on_evict → drop + forget
        assert eng.get_stats()["kv_tiering"]["entries"] == 0
        run_turn(eng, "t2", "fresh start", "c")
        st = eng.get_stats()["kv_tiering"]
        assert st["promotions"] == 0         # nothing served the return
        eng.stop()
        sm.stop()

    def test_async_pipeline_interplay(self):
        """Demote/promote under the PR 10 pipeline (depth 2, simulated
        device latency): streams match the pin-resident baseline and
        the promotion still lands as a host hit."""
        from llmq_tpu.core.config import AsyncPipelineConfig

        def build(tiering, clock):
            tok = ByteTokenizer()
            ex = EchoExecutor(batch_size=4, page_size=8, num_pages=128,
                              max_pages_per_seq=16, eos_id=tok.eos_id,
                              chunk_size=4, async_chunks=True,
                              step_delay_s=0.001)
            return InferenceEngine(
                ex, tok, enable_metrics=False, name="tierpipe",
                kv_pin_ttl=5.0 if tiering else 600.0, clock=clock,
                kv_tiering=tiering,
                async_pipeline=AsyncPipelineConfig(enabled=True,
                                                   depth=2))

        outs = []
        for tiering in (None, KVTieringConfig(enabled=True)):
            clock = FakeClock()
            eng = build(tiering, clock)
            eng.start()
            h1 = eng.submit(GenRequest(id="t1", prompt="pipeline text",
                                       conversation_id="c",
                                       max_new_tokens=10))
            assert h1.wait(30.0)
            clock.advance(6.0)
            if tiering is not None:
                assert wait_until(
                    lambda: "c" not in eng.cached_conversations())
                assert wait_until(lambda: eng.get_stats()
                                  ["kv_tiering"]["host_entries"] == 1)
            h2 = eng.submit(GenRequest(id="t2", prompt=" and more",
                                       conversation_id="c",
                                       max_new_tokens=10))
            assert h2.wait(30.0)
            outs.append((h1.result.tokens, h2.result.tokens))
            if tiering is not None:
                st = eng.get_stats()["kv_tiering"]
                assert st["hits"]["host"] == 1, st
            eng.stop()
        assert outs[0] == outs[1]


# -- CPU-mode JAX engine integration -------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    from llmq_tpu.models.llama import init_params, llama3_tiny

    cfg = llama3_tiny(dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                      ffn_dim=128, vocab_size=512, max_seq_len=256)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def run_jax_two_turns(cfg, params, tiering_cfg, store=None, expire=True,
                      cache_dtype=None):
    tok = ByteTokenizer()
    ex = JaxExecutor(cfg, params, batch_size=2, page_size=8,
                     num_pages=64, prefill_buckets=[16, 64],
                     eos_id=tok.eos_id, chunk_size=4,
                     cache_dtype=cache_dtype)
    clock = FakeClock()
    eng = InferenceEngine(ex, tok, enable_metrics=False,
                          max_decode_steps=12, clock=clock,
                          kv_pin_ttl=5.0 if expire else 600.0,
                          kv_tiering=tiering_cfg)
    if store is not None and eng._tiering is not None:
        eng._tiering.store = store
    h1 = eng.submit(GenRequest(id="t1", prompt="the quick brown fox",
                               conversation_id="c", max_new_tokens=10))
    eng.run_until_idle()
    if expire:
        clock.advance(6.0)
        eng.step()
        assert "c" not in eng.cached_conversations()
        if eng._tiering is not None:
            assert wait_until(lambda: sum(
                eng._tiering.counts().values()) == 1)
    h2 = eng.submit(GenRequest(id="t2", prompt=" jumps over",
                               conversation_id="c", max_new_tokens=10))
    eng.run_until_idle()
    eng.stop()
    return eng, (h1, h2)


class TestJaxEngineTiering:
    def test_every_tier_token_for_token(self, tiny_model):
        """The acceptance pin: host-tier, store-tier and recompute
        promotions all decode turn 2 exactly like the pin-resident
        baseline (real KV payload round-trips bit-exact through the
        host pool and the store blob)."""
        cfg, params = tiny_model
        _, base = run_jax_two_turns(cfg, params, None, expire=False)
        base_toks = [h.result.tokens for h in base]
        assert all(base_toks)

        eng, out = run_jax_two_turns(cfg, params,
                                     KVTieringConfig(enabled=True))
        st = eng.get_stats()["kv_tiering"]
        assert st["hits"]["host"] == 1, st
        assert [h.result.tokens for h in out] == base_toks
        assert out[1].result.cached_tokens > 0

        eng, out = run_jax_two_turns(
            cfg, params,
            KVTieringConfig(enabled=True, host_capacity_mb=0),
            store=InMemoryStore())
        st = eng.get_stats()["kv_tiering"]
        assert st["spills"] == 1 and st["hits"]["store"] == 1, st
        assert [h.result.tokens for h in out] == base_toks

        eng, out = run_jax_two_turns(
            cfg, params,
            KVTieringConfig(enabled=True, host_capacity_mb=0,
                            store_spill=False))
        st = eng.get_stats()["kv_tiering"]
        assert st["hits"]["recompute"] == 1, st
        assert [h.result.tokens for h in out] == base_toks

    def test_int8_kv_payload_roundtrip(self, tiny_model):
        """int8-KV: the quantization scale pools ride the payload as
        ordinary cache leaves — promotion restores values AND scales."""
        import dataclasses

        import jax.numpy as jnp

        cfg, params = tiny_model
        cfg = dataclasses.replace(cfg, pallas=False)
        _, base = run_jax_two_turns(cfg, params, None, expire=False,
                                    cache_dtype=jnp.int8)
        eng, out = run_jax_two_turns(cfg, params,
                                     KVTieringConfig(enabled=True),
                                     cache_dtype=jnp.int8)
        st = eng.get_stats()["kv_tiering"]
        assert st["hits"]["host"] == 1, st
        assert [h.result.tokens for h in out] == [h.result.tokens
                                                  for h in base]
        # The payload spec carried all four leaves.
        specs = eng.executor.kv_page_spec()
        assert len(specs) == 4

    def test_off_switch_matches_no_tiering(self, tiny_model):
        """enabled:false is byte-identical to a pre-plane engine: no
        plane object, no worker thread, same streams."""
        cfg, params = tiny_model
        before = {t.name for t in threading.enumerate()}
        eng_off, off = run_jax_two_turns(
            cfg, params, KVTieringConfig(enabled=False), expire=False)
        assert eng_off._tiering is None
        assert not [t for t in threading.enumerate()
                    if t.name.startswith("kv-tiering")
                    and t.name not in before]
        _, none = run_jax_two_turns(cfg, params, None, expire=False)
        assert [h.result.tokens for h in off] == [h.result.tokens
                                                  for h in none]


# -- metrics -------------------------------------------------------------------


class TestTieringMetrics:
    def test_families_exposed_and_hits_counted(self):
        from llmq_tpu.metrics.registry import exposition

        clock = FakeClock()
        eng = mk_echo_engine(tiering=KVTieringConfig(enabled=True),
                             pin_ttl=5.0, clock=clock, metrics=True)
        run_turn(eng, "t1", "metric text", "c")
        clock.advance(6.0)
        eng.step()
        run_turn(eng, "t2", " more", "c")
        exp = exposition().decode()
        for fam in ("llm_queue_kv_tier_pages",
                    "llm_queue_kv_tier_bytes",
                    "llm_queue_kv_tier_hits_total",
                    "llm_queue_kv_tier_round_trips_total",
                    "llm_queue_kv_promote_ms",
                    "llm_queue_kv_demote_ms"):
            assert fam in exp, fam
        assert ('llm_queue_kv_tier_hits_total{engine="tiertest",'
                'tier="host"}') in exp
        assert ('llm_queue_kv_demote_ms_count{engine="tiertest"}'
                ) in exp
        eng.stop()


# -- cross-OS-process blob handoff over real HTTP (satellite) ------------------


class TestCrossProcessBlobHandoff:
    """The disagg exchange's transport-level contract: a blob encoded
    in one OS process survives a REAL network hop and decodes in
    another process bit-identically — including the int8 KV pages and
    their float32 scale pool — and a blob torn in transit raises (the
    importer degrades to recompute, never injects garbage)."""

    def test_http_transfer_int8_scales_bit_identical(self):
        import http.server
        import os
        import subprocess
        import sys

        rng = np.random.default_rng(33)
        n_pages = 4
        # An int8-quantized cache tree: quantized pages + their scale
        # pool, riding as ordinary leaves with their own specs.
        pages_i8 = rng.integers(-128, 128, (2, n_pages, 8, 16)
                                ).astype(np.int8)
        scales = rng.random((2, n_pages, 8)).astype(np.float32)
        leaves = [pages_i8, scales]
        specs = [((leaf.shape[0],) + leaf.shape[2:], leaf.dtype)
                 for leaf in leaves]
        per = page_payload_nbytes(specs)
        bufs = [np.empty(per, np.uint8) for _ in range(n_pages)]
        pack_pages(leaves, bufs)
        blob = encode_blob(bufs, specs,
                           meta={"conv_id": "c", "tokens": [1, 2, 3],
                                 "length": 3, "n_pages": n_pages})

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                # /blob: the published entry; /torn: cut mid-payload,
                # as a crashed publisher/partial write would leave it.
                body = blob if self.path == "/blob" else blob[:-16]
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        port = srv.server_address[1]

        child = f"""
import urllib.request
import numpy as np
from llmq_tpu.tiering import decode_blob, unpack_pages
from llmq_tpu.tiering.plane import blob_meta

with urllib.request.urlopen(
        "http://127.0.0.1:{port}/blob", timeout=10) as r:
    blob = r.read()
meta = blob_meta(blob)
assert meta["tokens"] == [1, 2, 3], meta
bufs, specs = decode_blob(blob)
leaves = unpack_pages(bufs, specs)
rng = np.random.default_rng(33)
want_i8 = rng.integers(-128, 128, (2, {n_pages}, 8, 16)).astype(np.int8)
want_sc = rng.random((2, {n_pages}, 8)).astype(np.float32)
assert leaves[0].dtype == np.int8
assert np.array_equal(leaves[0], want_i8)
print("PAYLOAD_OK", flush=True)
# Bit-identity of the scale pool: byte-level comparison, not almost-
# equal — a single flipped mantissa bit would dequantize every value
# in the page.
assert leaves[1].dtype == np.float32
assert np.array_equal(leaves[1].view(np.uint8), want_sc.view(np.uint8))
print("SCALES_BIT_IDENTICAL", flush=True)
with urllib.request.urlopen(
        "http://127.0.0.1:{port}/torn", timeout=10) as r:
    torn = r.read()
try:
    decode_blob(torn)
except ValueError:
    print("TORN_DEGRADES_TO_RECOMPUTE", flush=True)
else:
    raise AssertionError("torn blob decoded")
"""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        try:
            out = subprocess.run(
                [sys.executable, "-c", child],
                cwd=os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                env=env, capture_output=True, text=True, timeout=120)
        finally:
            srv.shutdown()
        assert out.returncode == 0, out.stderr
        assert "PAYLOAD_OK" in out.stdout
        assert "SCALES_BIT_IDENTICAL" in out.stdout
        assert "TORN_DEGRADES_TO_RECOMPUTE" in out.stdout
