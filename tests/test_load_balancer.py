"""LoadBalancer tests.

Mirrors reference tests/loadbalancer_test.go: all 4 strategies (RR
fairness :18-64, least-conn :67-105, weighted-random distribution over
1000 draws :108-150, adaptive best-endpoint :153-197), health filtering +
status update (:200-253), add/remove (:256-306), session affinity
(:309-366) — plus the real health-probe state machine the reference
stubs."""

import random

import pytest

from llmq_tpu.core.config import LoadBalancerConfig
from llmq_tpu.core.errors import NoEndpointError
from llmq_tpu.core.types import Message
from llmq_tpu.loadbalancer import Endpoint, EndpointStatus, LoadBalancer


def make_lb(strategy="round_robin", fake_clock=None, probe=None, seed=7,
            session_affinity=True):
    cfg = LoadBalancerConfig(strategy=strategy, health_check_interval=0,
                             session_affinity=session_affinity)
    return LoadBalancer(cfg, clock=fake_clock, probe=probe,
                        rng=random.Random(seed))


def eps(n, **kw):
    return [Endpoint(id=f"e{i}", url=f"local://e{i}", **kw) for i in range(n)]


class TestRoundRobin:
    def test_fairness(self, fake_clock):
        lb = make_lb("round_robin", fake_clock)
        for e in eps(3):
            lb.add_endpoint(e)
        picks = [lb.get_endpoint().id for _ in range(9)]
        assert picks.count("e0") == picks.count("e1") == picks.count("e2") == 3

    def test_per_type_cursor(self, fake_clock):
        lb = make_lb("round_robin", fake_clock)
        lb.add_endpoint(Endpoint(id="a0", model_type="llm"))
        lb.add_endpoint(Endpoint(id="a1", model_type="llm"))
        lb.add_endpoint(Endpoint(id="b0", model_type="embed"))
        m = Message(metadata={"model_type": "embed"})
        assert lb.get_endpoint(m).id == "b0"
        assert lb.get_endpoint().id in ("a0", "a1")


class TestLeastConnections:
    def test_picks_least_busy(self, fake_clock):
        lb = make_lb("least_connections", fake_clock)
        for e in eps(3):
            lb.add_endpoint(e)
        lb.get_endpoint_by_id("e0").connections = 5
        lb.get_endpoint_by_id("e1").connections = 1
        lb.get_endpoint_by_id("e2").connections = 3
        assert lb.get_endpoint().id == "e1"


class TestWeightedRandom:
    def test_distribution(self, fake_clock):
        lb = make_lb("weighted_random", fake_clock, session_affinity=False)
        lb.add_endpoint(Endpoint(id="heavy", weight=9.0))
        lb.add_endpoint(Endpoint(id="light", weight=1.0))
        picks = []
        for _ in range(1000):
            ep = lb.get_endpoint()
            picks.append(ep.id)
            lb.release_endpoint(ep.id)
        frac_heavy = picks.count("heavy") / 1000
        assert 0.8 < frac_heavy < 0.98  # statistical, mirrors :108-150


class TestAdaptive:
    def test_picks_best_scored(self, fake_clock):
        lb = make_lb("adaptive_load", fake_clock, seed=1)
        lb.add_endpoint(Endpoint(id="bad", response_time=2.0, error_rate=0.5))
        lb.add_endpoint(Endpoint(id="good", response_time=0.1, error_rate=0.0))
        wins = sum(lb.get_endpoint().id == "good" for _ in range(50))
        assert wins >= 40  # 10% exploration allowed


class TestHealthFiltering:
    def test_unhealthy_excluded(self, fake_clock):
        lb = make_lb("round_robin", fake_clock)
        for e in eps(2):
            lb.add_endpoint(e)
        lb.set_endpoint_status("e0", EndpointStatus.UNHEALTHY)
        assert all(lb.get_endpoint().id == "e1" for _ in range(5))

    def test_degraded_still_selectable(self, fake_clock):
        lb = make_lb("round_robin", fake_clock)
        lb.add_endpoint(Endpoint(id="e0", status=EndpointStatus.DEGRADED))
        assert lb.get_endpoint().id == "e0"

    def test_no_endpoint_raises(self, fake_clock):
        lb = make_lb(fake_clock=fake_clock)
        with pytest.raises(NoEndpointError):
            lb.get_endpoint()

    def test_max_connections_respected(self, fake_clock):
        lb = make_lb("round_robin", fake_clock)
        lb.add_endpoint(Endpoint(id="e0", max_connections=1))
        lb.get_endpoint()
        with pytest.raises(NoEndpointError):
            lb.get_endpoint()


class TestHealthProbe:
    def test_state_machine(self, fake_clock):
        # Fix of the reference's always-healthy stub (:588-616).
        health = {"ok": True}
        lb = make_lb(fake_clock=fake_clock, probe=lambda ep: health["ok"])
        lb.add_endpoint(Endpoint(id="e0"))
        health["ok"] = False
        lb.check_health_once()
        assert lb.get_endpoint_by_id("e0").status == EndpointStatus.DEGRADED
        lb.check_health_once()
        lb.check_health_once()
        assert lb.get_endpoint_by_id("e0").status == EndpointStatus.UNHEALTHY
        # Recovery passes through degraded.
        health["ok"] = True
        lb.check_health_once()
        assert lb.get_endpoint_by_id("e0").status == EndpointStatus.UNHEALTHY
        lb.check_health_once()
        assert lb.get_endpoint_by_id("e0").status == EndpointStatus.DEGRADED
        lb.check_health_once()
        lb.check_health_once()
        assert lb.get_endpoint_by_id("e0").status == EndpointStatus.HEALTHY

    def test_probe_crash_counts_as_failure(self, fake_clock):
        def bad_probe(ep):
            raise RuntimeError("probe broke")
        lb = make_lb(fake_clock=fake_clock, probe=bad_probe)
        lb.add_endpoint(Endpoint(id="e0"))
        lb.check_health_once()
        assert lb.get_endpoint_by_id("e0").status == EndpointStatus.DEGRADED


class TestAddRemove:
    def test_add_remove(self, fake_clock):
        lb = make_lb(fake_clock=fake_clock)
        lb.add_endpoint(Endpoint(id="e0"))
        assert lb.remove_endpoint("e0")
        assert not lb.remove_endpoint("e0")
        assert lb.endpoints() == []

    def test_remove_clears_sessions(self, fake_clock):
        lb = make_lb(fake_clock=fake_clock)
        lb.add_endpoint(Endpoint(id="e0"))
        lb.get_endpoint(session_id="s1")
        assert lb.get_session_endpoint("s1") is not None
        lb.remove_endpoint("e0")
        assert lb.get_session_endpoint("s1") is None


class TestSessionAffinity:
    def test_sticky(self, fake_clock):
        lb = make_lb("round_robin", fake_clock)
        for e in eps(3):
            lb.add_endpoint(e)
        first = lb.get_endpoint(session_id="conv-1").id
        for _ in range(5):
            assert lb.get_endpoint(session_id="conv-1").id == first

    def test_ttl_expiry(self, fake_clock):
        lb = make_lb("round_robin", fake_clock)
        lb.config.session_ttl = 10.0
        for e in eps(2):
            lb.add_endpoint(e)
        lb.get_endpoint(session_id="s")
        fake_clock.advance(11.0)
        assert lb.cleanup_sessions() == 1
        assert lb.session_count() == 0

    def test_affinity_skips_unhealthy(self, fake_clock):
        lb = make_lb("round_robin", fake_clock)
        for e in eps(2):
            lb.add_endpoint(e)
        first = lb.get_endpoint(session_id="s").id
        lb.set_endpoint_status(first, EndpointStatus.UNHEALTHY)
        other = lb.get_endpoint(session_id="s").id
        assert other != first


class TestRelease:
    def test_ewma_and_error_decay(self, fake_clock):
        lb = make_lb(fake_clock=fake_clock)
        lb.add_endpoint(Endpoint(id="e0"))
        lb.get_endpoint()
        lb.release_endpoint("e0", response_time=1.0)
        assert lb.get_endpoint_by_id("e0").response_time == 1.0
        lb.get_endpoint()
        lb.release_endpoint("e0", response_time=2.0)
        # EWMA 9:1 (:311-317).
        assert lb.get_endpoint_by_id("e0").response_time == pytest.approx(1.1)
        lb.get_endpoint()
        lb.release_endpoint("e0", is_error=True)
        assert lb.get_endpoint_by_id("e0").error_rate == pytest.approx(0.1)
        lb.get_endpoint()
        lb.release_endpoint("e0")
        assert lb.get_endpoint_by_id("e0").error_rate == pytest.approx(0.095)
        assert lb.get_endpoint_by_id("e0").connections == 0
