"""Mesh-native serving executor (ISSUE 15, ROADMAP item 1).

The full serving stack on the virtual 8-device CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``, pinned by
conftest): dp2×tp4 serves the mixed workload — prefill waves, decode,
prefix continuation, preemption, tiering demote/promote, async
pipeline depth 2 — token-for-token identical to the single-chip
engine; the paged pool's page axis genuinely splits into per-replica
universes mirrored by the host allocator; the warmup/export cache is
keyed on the mesh geometry; and ``executor.mesh.enabled=false`` keeps
the exact single-chip path.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from llmq_tpu.core.config import MeshConfig, default_config  # noqa: E402
from llmq_tpu.core.types import Priority  # noqa: E402
from llmq_tpu.engine.engine import GenRequest, InferenceEngine  # noqa: E402
from llmq_tpu.engine.executor import JaxExecutor  # noqa: E402
from llmq_tpu.engine.kv_allocator import PageAllocator  # noqa: E402
from llmq_tpu.engine.tokenizer import ByteTokenizer  # noqa: E402
from llmq_tpu.models.llama import init_params, llama3_tiny  # noqa: E402
from llmq_tpu.parallel import make_mesh  # noqa: E402
from llmq_tpu.parallel.sharding import (  # noqa: E402
    LLAMA_PARTITION_RULES,
    kv_cache_shardings,
    match_partition_rules,
    param_shardings,
    resolve_rules,
)

P = jax.sharding.PartitionSpec


def tp_cfg(**kw):
    # Head/ffn/vocab counts divisible by tp=4 AND tp=8 so the sharding
    # is real on every axis in both geometries.
    defaults = dict(dim=256, n_heads=8, n_kv_heads=8, ffn_dim=512,
                    vocab_size=512, max_seq_len=256)
    defaults.update(kw)
    return llama3_tiny(**defaults)


@pytest.fixture(scope="module")
def tiny(request):
    cfg = tp_cfg()
    return cfg, init_params(jax.random.PRNGKey(0), cfg)


@pytest.fixture(scope="module")
def wave_reference(tiny):
    """Single-chip reference run (one engine build for the module):
    WAVE results plus both conversations' turn 2 — what every mesh
    geometry must reproduce token-for-token."""
    cfg, params = tiny
    eng = build_engine(cfg, params, None)
    wave = run_requests(eng, WAVE)
    assert all(r.finish_reason in ("eos", "length") for r in wave)
    t2 = run_requests(eng, [dict(id="a2", prompt=" more",
                                 conversation_id="c1"),
                            dict(id="c2t", prompt=" again",
                                 conversation_id="c2")])
    out = {"wave": [r.tokens for r in wave],
           "wave_text": [r.text for r in wave],
           "turn2_tokens": [r.tokens for r in t2],
           "turn2_cached": [r.cached_tokens for r in t2],
           "preempt": run_preemption_phase(eng)}
    eng.stop()
    return out


def run_requests(engine, reqs):
    handles = [engine.submit(GenRequest(**r)) for r in reqs]
    engine.run_until_idle()
    return [h.result for h in handles]


def run_preemption_phase(engine):
    """Deterministic preemption choreography: fill every slot with LOW
    decoders, let them run a step, then land REALTIME arrivals — the
    late urgents must preempt. Final tokens are timing-independent
    (slot preemption resumes exactly), so mesh and single-chip engines
    compare even though their step cadence differs.

    The prompt text matters: comparing DIFFERENT partitionings of the
    same bf16 math (tp4 vs one chip) is exact only while no argmax
    lands on a reduction-order near-tie — the same property every
    mesh-equivalence pin in this repo (test_engine_tp.py included)
    relies on. This workload is verified tie-free on dp2×tp4/tp4; a
    flip here after a model change means re-picking prompts, not a
    sharding bug (dp2×tp4 vs tp4-subset stays EXACTLY equal either
    way — the dp machinery adds no arithmetic)."""
    lows = [engine.submit(GenRequest(
        id=f"L{i}", prompt=f"steady background work {i}",
        priority=Priority.LOW, max_new_tokens=12)) for i in range(4)]
    # One step: the wave is seated (slots held, prefills dispatched)
    # but far from done — the urgents land mid-flight.
    engine.step()
    rts = [engine.submit(GenRequest(
        id=f"R{i}", prompt=f"urgent {i}", priority=Priority.REALTIME,
        max_new_tokens=6)) for i in range(2)]
    engine.run_until_idle()
    return [h.result.tokens for h in lows + rts]


def wait_until(fn, timeout=5.0, step=0.002):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


# -- partition-rule table ------------------------------------------------------


class TestPartitionRules:
    def test_rules_match_expected_layout(self, tiny):
        cfg, params = tiny
        specs = match_partition_rules(LLAMA_PARTITION_RULES, params)
        lay = specs["layers"]
        assert lay["wq"] == P(None, None, "tp")
        assert lay["wo"] == P(None, "tp", None)
        assert lay["w_down"] == P(None, "tp", None)
        assert lay["attn_norm"] == P()
        assert specs["embed"] == P("tp", None)

    def test_unmatched_param_raises(self):
        with pytest.raises(ValueError, match="no partition rule"):
            match_partition_rules(
                [(r"^only_this$", P())],
                {"mystery": np.zeros((4, 4), np.float32)})

    def test_divisibility_clamps_to_replication(self):
        """An axis the mesh can't divide evenly replicates — the rule
        still names tp, the resolver clamps exactly that axis."""
        mesh = make_mesh({"tp": 8})
        cfg = llama3_tiny(ffn_dim=84)      # 84 % 8 != 0
        sh = param_shardings(cfg, mesh)
        assert sh["layers"]["w_gate"].spec == P(None, None, None)
        assert sh["layers"]["w_down"].spec == P(None, None, None)
        assert sh["layers"]["wq"].spec == P(None, None, "tp")
        # The KV cache's head axis (n_kv_heads=2) can't split 8 ways
        # either — the pool replicates while wq stays sharded.
        assert kv_cache_shardings(cfg, mesh)["k"].spec == P(
            None, None, None, None)

    def test_quantized_scale_rides_weight_rule(self, tiny):
        """{q, s} leaves take the weight's named axes; the size-1
        contraction axis of the scale clamps to replication."""
        mesh = make_mesh({"dp": 2, "tp": 4})
        cfg, _ = tiny
        sh = param_shardings(cfg, mesh, quantized=True)
        assert sh["layers"]["wo"]["q"].spec == P(None, "tp", None)
        assert sh["layers"]["wo"]["s"].spec == P(None, None, None)
        assert sh["layers"]["wq"]["s"].spec == P(None, None, "tp")

    def test_resolve_rules_generic_tree(self):
        mesh = make_mesh({"dp": 2, "tp": 4})
        tree = {"a": np.zeros((8, 16), np.float32),
                "scalar": np.zeros((), np.float32)}
        out = resolve_rules([(r".", P("tp", None))], tree, mesh)
        assert out["a"].spec == P("tp", None)
        assert out["scalar"].spec == P()

    def test_kv_shardings_grow_dp_page_axis(self, tiny):
        cfg, _ = tiny
        mesh = make_mesh({"dp": 2, "tp": 4})
        kv = kv_cache_shardings(cfg, mesh, quantized=True, num_pages=64)
        assert kv["k"].spec == P(None, "dp", None, "tp")
        assert kv["k_scale"].spec == P(None, "dp", "tp", None)
        # num_pages not divisible by dp → page axis replicates.
        kv2 = kv_cache_shardings(cfg, mesh, num_pages=65)
        assert kv2["k"].spec == P(None, None, None, "tp")
        # Legacy call shape (no num_pages): unchanged layout.
        kv3 = kv_cache_shardings(cfg, mesh)
        assert kv3["k"].spec == P(None, None, None, "tp")


# -- dp page universes (host allocator) ----------------------------------------


class TestDpAllocator:
    def test_universe_ranges(self):
        al = PageAllocator(64, 16, dp_shards=2)
        assert al.pages_per_shard == 32
        a = al.alloc(3, shard=0)
        b = al.alloc(3, shard=1)
        assert all(1 <= p < 32 for p in a)
        assert all(32 <= p < 64 for p in b)
        assert [al.shard_of(p) for p in a + b] == [0, 0, 0, 1, 1, 1]

    def test_page0_reserved_only_in_shard0(self):
        al = PageAllocator(8, 16, dp_shards=2)
        assert al.available(shard=0) == 3   # 1..3
        assert al.available(shard=1) == 4   # 4..7
        assert al.available() == 7 == al.total

    def test_all_or_nothing_per_universe(self):
        al = PageAllocator(8, 16, dp_shards=2)
        assert al.alloc(4, shard=1) is not None
        # Shard 1 exhausted: a pinned alloc fails even though shard 0
        # has room (the caller decides whether to fall back).
        assert al.alloc(1, shard=1) is None
        assert al.alloc(1, shard=0) is not None

    def test_unpinned_alloc_picks_fullest_universe(self):
        al = PageAllocator(8, 16, dp_shards=2)
        assert al.alloc(2, shard=0) is not None   # shard0: 1 left
        pages = al.alloc(1)
        assert al.shard_of(pages[0]) == 1

    def test_free_returns_to_owning_universe(self):
        al = PageAllocator(16, 16, dp_shards=2)
        pages = al.alloc(8, shard=1)
        assert al.available(shard=1) == 0
        al.free(pages)
        assert al.available(shard=1) == 8
        assert al.available_by_shard() == [7, 8]

    def test_dp1_is_byte_identical_to_unsharded(self):
        old_like = PageAllocator(16, 16)
        new = PageAllocator(16, 16, dp_shards=1)
        for _ in range(3):
            assert old_like.alloc(4) == new.alloc(4)
        assert old_like.available() == new.available()

    def test_indivisible_pages_raise(self):
        with pytest.raises(ValueError, match="dp shards"):
            PageAllocator(65, 16, dp_shards=2)

    def test_bad_shard_raises(self):
        al = PageAllocator(16, 16, dp_shards=2)
        with pytest.raises(ValueError, match="bad dp shard"):
            al.alloc(1, shard=2)


# -- end-to-end equivalence ----------------------------------------------------


WAVE = [
    # More requests than slots → pending heap + admission waves; mixed
    # tiers → preemption pressure; two conversations → continuation.
    dict(id="a", prompt="hello tensor parallel mesh",
         conversation_id="c1"),
    dict(id="b", prompt="second request", priority=Priority.REALTIME),
    dict(id="c", prompt="third one", conversation_id="c2"),
    dict(id="d", prompt="a rather longer prompt that streams through "
                        "more than one prefill chunk easily",
         priority=Priority.LOW),
    dict(id="e", prompt="fifth", priority=Priority.REALTIME),
    dict(id="f", prompt="sixth request runs too"),
]


def build_engine(cfg, params, mesh=None, *, pipeline=None, mixed=None,
                 tiering=None, clock=None, pin_ttl=600.0,
                 batch_size=4, num_pages=64, max_decode_steps=8):
    from llmq_tpu.core.config import PrefixCacheConfig

    tok = ByteTokenizer()
    kw = dict(batch_size=batch_size, page_size=16, num_pages=num_pages,
              chunk_size=4, prefill_buckets=[32], eos_id=tok.eos_id)
    if mixed is not None:
        kw.update(mixed_prefill_slices=mixed.max_slices,
                  mixed_slice_tokens=mixed.slice_tokens)
    ex = JaxExecutor(cfg, params, mesh=mesh, **kw)
    eng = InferenceEngine(
        ex, tok, name="mesh" if mesh is not None else "one",
        enable_metrics=False, max_decode_steps=max_decode_steps,
        prefix_cache=PrefixCacheConfig(enabled=True),
        mixed_batch=mixed, async_pipeline=pipeline,
        kv_tiering=tiering, clock=clock, kv_pin_ttl=pin_ttl)
    return eng


class TestMeshServing:
    def test_dp2tp4_mixed_workload_token_identical(self, tiny,
                                                   wave_reference):
        """The acceptance pin: waves + decode + prefix continuation +
        preemption + 2-deep async pipeline + mixed batching, dp2×tp4
        vs the single-chip reference, token-for-token. The mesh engine
        runs with the pipeline AND mixed batching ON against a plain
        reference — the whole composition must still be exact."""
        from llmq_tpu.core.config import (AsyncPipelineConfig,
                                          MixedBatchConfig)

        cfg, params = tiny
        mesh = make_mesh({"dp": 2, "tp": 4})
        pipe = AsyncPipelineConfig(enabled=True, depth=2)
        mixed = MixedBatchConfig(enabled=True, prefill_token_budget=32,
                                 max_slices=2)
        eng_m = build_engine(cfg, params, mesh, pipeline=pipe,
                             mixed=mixed)

        # The sharding is real: dp splits the pool's page axis, tp the
        # KV-head axis — each chip holds 1/8 of the cache.
        ex = eng_m.executor
        assert ex.dp_shards == 2
        kv = ex.cache["k"]
        assert kv.sharding.spec == P(None, "dp", None, "tp")
        shard_shape = kv.addressable_shards[0].data.shape
        assert shard_shape[1] == kv.shape[1] // 2
        assert shard_shape[3] == kv.shape[3] // 4

        res_m = run_requests(eng_m, WAVE)
        for i, r_m in enumerate(res_m):
            assert r_m.finish_reason in ("eos", "length")
            assert r_m.tokens == wave_reference["wave"][i]
            assert r_m.text == wave_reference["wave_text"][i]

        # Prefix continuation over the dp-sharded pool: turn 2 of both
        # conversations adopts cached KV and still matches.
        t2 = [dict(id="a2", prompt=" more", conversation_id="c1"),
              dict(id="c2t", prompt=" again", conversation_id="c2")]
        r2_m = run_requests(eng_m, t2)
        for i, r_m in enumerate(r2_m):
            assert r_m.cached_tokens > 0
            assert r_m.cached_tokens == wave_reference["turn2_cached"][i]
            assert r_m.tokens == wave_reference["turn2_tokens"][i]

        # Late-arriving REALTIME over a full batch: preemption REALLY
        # fires on the mesh engine, and every stream still matches.
        preempts = []
        orig = eng_m._preempt
        eng_m._preempt = (  # type: ignore[method-assign]
            lambda victim, release_pages: (
                preempts.append(victim.req.id),
                orig(victim, release_pages))[-1])
        toks = run_preemption_phase(eng_m)
        assert preempts, "no preemption occurred on the mesh engine"
        assert toks == wave_reference["preempt"]
        eng_m.stop()

    def test_dp_page_locality(self, tiny):
        """Rows in dp shard d draw pages from universe d: serve one
        request per slot and check every live sequence's pages against
        its slot's universe."""
        cfg, params = tiny
        mesh = make_mesh({"dp": 2, "tp": 4})
        eng = build_engine(cfg, params, mesh, max_decode_steps=64)
        reqs = [GenRequest(id=f"s{i}", prompt=f"slot filler {i}",
                           max_new_tokens=48) for i in range(4)]
        handles = [eng.submit(r) for r in reqs]
        # Step until every slot is seated and prefilled, then verify
        # locality while the sequences are still live.
        for _ in range(200):
            eng.step()
            seated = [s for s in eng._slots if s is not None and s.pages]
            if len(seated) == 4:
                break
        checked = 0
        for slot, seq in enumerate(eng._slots):
            if seq is None or not seq.pages:
                continue
            want = eng._slot_shard(slot)
            for p in seq.pages:
                assert eng.allocator.shard_of(p) == want, (slot, p)
            checked += 1
        assert checked == 4
        eng.run_until_idle()
        assert all(h.result is not None for h in handles)
        eng.stop()

    def test_tiering_demote_promote_equivalence(self, tiny):
        """HBM→host demotion and promotion over the dp-sharded pool:
        turn 2 after a pin expiry is token-for-token the resident-pin
        baseline (the KV payload round-trips through the host tier of
        a mesh executor)."""
        from llmq_tpu.core.clock import FakeClock
        from llmq_tpu.core.config import KVTieringConfig

        cfg, params = tiny
        outs = []
        for tiering in (None, KVTieringConfig(enabled=True)):
            mesh = make_mesh({"dp": 2, "tp": 4})
            clock = FakeClock()
            eng = build_engine(cfg, params, mesh, tiering=tiering,
                               clock=clock,
                               pin_ttl=5.0 if tiering else 600.0,
                               max_decode_steps=10)
            h1 = eng.submit(GenRequest(id="t1",
                                       prompt="the quick brown fox",
                                       conversation_id="c",
                                       max_new_tokens=8))
            eng.run_until_idle()
            if tiering is not None:
                clock.advance(6.0)
                eng.step()
                assert "c" not in eng.cached_conversations()
                assert wait_until(lambda: sum(
                    eng._tiering.counts().values()) == 1)
            h2 = eng.submit(GenRequest(id="t2", prompt=" jumps over",
                                       conversation_id="c",
                                       max_new_tokens=8))
            eng.run_until_idle()
            if tiering is not None:
                st = eng.get_stats()["kv_tiering"]
                assert st["hits"]["host"] == 1, st
                assert h2.result.cached_tokens > 0
            outs.append((h1.result.tokens, h2.result.tokens))
            eng.stop()
        assert outs[0] == outs[1]

    def test_tp4_subset_mesh_serves(self, tiny, wave_reference):
        """tp4 over a 4-device subset of the 8 — the second CI-lane
        geometry: a mesh need not span every visible device. (tp8
        equivalence incl. continuation is test_engine_tp.py's pin.)"""
        cfg, params = tiny
        mesh = make_mesh({"tp": 4}, devices=jax.devices()[:4])
        eng_m = build_engine(cfg, params, mesh)
        assert eng_m.executor.dp_shards == 1
        res_m = run_requests(eng_m, WAVE[:2])
        for r_m, toks in zip(res_m, wave_reference["wave"][:2]):
            assert r_m.tokens == toks
        assert len(eng_m.executor.hbm_info()) == 4
        eng_m.stop()

    def test_indivisible_dp_degrades_to_replication(self, tiny):
        """dp that doesn't divide the batch/pool builds with dp as pure
        replication (correctness first) — the executor reports it and
        the allocator keeps one universe."""
        cfg, params = tiny
        mesh = make_mesh({"dp": 2, "tp": 4})
        tok = ByteTokenizer()
        ex = JaxExecutor(cfg, params, mesh=mesh, batch_size=3,
                         page_size=16, num_pages=65, chunk_size=4,
                         prefill_buckets=[32], eos_id=tok.eos_id)
        assert ex.dp_shards == 1
        assert ex.cache["k"].sharding.spec == P(None, None, None, "tp")


# -- per-chip HBM accounting ---------------------------------------------------


class TestPerChipHbm:
    def test_truthful_split_dp2tp4(self, tiny):
        cfg, params = tiny
        mesh = make_mesh({"dp": 2, "tp": 4})
        tok = ByteTokenizer()
        ex = JaxExecutor(cfg, params, mesh=mesh, batch_size=4,
                         page_size=16, num_pages=64, chunk_size=4,
                         prefill_buckets=[32], eos_id=tok.eos_id)
        chips = ex.hbm_info()
        assert len(chips) == 8
        total_kv = sum(leaf.size * leaf.dtype.itemsize
                       for leaf in jax.tree.leaves(ex.cache))
        # KV: page axis /dp × head axis /tp → every chip holds exactly
        # 1/8; the per-chip reports SUM to the true pool size (no
        # double-count).
        assert all(c["kv_pool_bytes"] == total_kv // 8 for c in chips)
        assert sum(c["kv_pool_bytes"] for c in chips) == total_kv
        # Weights: tp shards the big matmuls, dp REPLICATES — each
        # chip truthfully reports its tp shard (norms replicated), and
        # chips within/across dp replicas agree.
        w = {c["weights_bytes"] for c in chips}
        assert len(w) == 1
        total_w = sum(leaf.size * leaf.dtype.itemsize
                      for leaf in jax.tree.leaves(params))
        per_chip = w.pop()
        assert total_w / 4 * 0.9 < per_chip < total_w / 4 * 1.2
        assert per_chip < total_w / 2    # replication not double-counted

    def test_hbm_gauge_cardinality_contract(self, tiny):
        """The per-chip gauge families stay within the label contract:
        one series per (engine, chip), chip ids are the 8 local
        devices, and a scrape after serving carries all of them."""
        from llmq_tpu.metrics.registry import get_metrics
        from llmq_tpu.observability.device import get_device_telemetry

        cfg, params = tiny
        mesh = make_mesh({"dp": 2, "tp": 4})
        tok = ByteTokenizer()
        ex = JaxExecutor(cfg, params, mesh=mesh, batch_size=4,
                         page_size=16, num_pages=64, chunk_size=4,
                         prefill_buckets=[32], eos_id=tok.eos_id,
                         telemetry_name="meshhbm",
                         telemetry_metrics=True)
        eng = InferenceEngine(ex, tok, name="meshhbm",
                              enable_metrics=True, max_decode_steps=4)
        run_requests(eng, [dict(id="x", prompt="hello")])
        get_device_telemetry("meshhbm").flush()
        m = get_metrics()
        fams = {"hbm_weights_bytes": m.hbm_weights_bytes,
                "hbm_kv_pool_bytes": m.hbm_kv_pool_bytes}
        for name, fam in fams.items():
            chip_ids = set()
            for metric in fam.collect():
                for s in metric.samples:
                    if s.labels.get("engine") != "meshhbm":
                        continue
                    chip_ids.add(s.labels["chip"])
            want = {str(d.id) for d in jax.local_devices()}
            assert chip_ids == want, (name, chip_ids)
        eng.stop()


# -- mesh-keyed warmup/export cache --------------------------------------------


class TestMeshExportCacheKey:
    def _executor(self, mesh, **kw):
        # The key/cache behavior doesn't need shardable head counts --
        # the smallest tiny model keeps the five warmups cheap.
        cfg = llama3_tiny()
        params = init_params(jax.random.PRNGKey(0), cfg)
        tok = ByteTokenizer()
        args = dict(batch_size=2, page_size=16, num_pages=34,
                    chunk_size=2, prefill_buckets=[16],
                    eos_id=tok.eos_id)
        args.update(kw)
        return JaxExecutor(cfg, params, mesh=mesh, **args)

    def test_key_changes_with_mesh_geometry(self):
        k_single = self._executor(None)._export_cache_key()
        k_tp8 = self._executor(make_mesh({"tp": 8}))._export_cache_key()
        dp2tp4 = self._executor(make_mesh({"dp": 2, "tp": 4}))
        keys = {k_single, k_tp8, dp2tp4._export_cache_key()}
        assert len(keys) == 3
        # Deterministic per geometry.
        again = self._executor(make_mesh({"dp": 2, "tp": 4}))
        assert again._export_cache_key() == dp2tp4._export_cache_key()

    def test_mesh_keying_end_to_end(self, tmp_path, monkeypatch):
        """One flow over a real export dir: a cache primed single-chip
        HITS on a single-chip rebuild but MISSES (0 hits) when the
        same model builds on a mesh; the mesh's own artifacts hit on
        the same geometry and MISS after a reshape (mirrors the PR 13
        stale-bucket pin)."""
        monkeypatch.setenv("LLMQ_EXPORT_CACHE_DIR", str(tmp_path))
        ex1 = self._executor(None)
        ex1.warmup()
        assert not ex1._from_export_cache
        assert any(f.suffix == ".jaxexp" for f in tmp_path.iterdir())

        ex2 = self._executor(None)
        ex2.warmup()
        assert ex2._from_export_cache        # same geometry -> hits

        exm = self._executor(make_mesh({"dp": 2, "tp": 4}))
        exm.warmup()
        assert not exm._from_export_cache    # single-chip prime -> MISS

        exm2 = self._executor(make_mesh({"dp": 2, "tp": 4}))
        exm2.warmup()
        assert exm2._from_export_cache       # same mesh -> its artifacts

        ex8 = self._executor(make_mesh({"tp": 8}))
        ex8.warmup()
        assert not ex8._from_export_cache    # reshaped mesh -> MISS


# -- config / builder off-switch -----------------------------------------------


class TestMeshConfig:
    def test_defaults_off(self):
        cfg = default_config()
        assert cfg.executor.mesh.enabled is False
        assert cfg.executor.mesh.shape == {}

    def test_validation(self):
        with pytest.raises(ValueError, match="dp' or 'tp"):
            MeshConfig(shape={"zz": 2})
        with pytest.raises(ValueError, match="positive int"):
            MeshConfig(shape={"dp": 0})
        with pytest.raises(ValueError, match="requires a shape"):
            MeshConfig(enabled=True)
        MeshConfig(enabled=True, shape={"dp": 2, "tp": -1})

    def test_builder_executor_mesh_block(self):
        from llmq_tpu.engine.builder import build_engine

        cfg = default_config()
        cfg.executor.backend = "jax"
        cfg.executor.max_batch_size = 4
        cfg.executor.kv_pages = 64
        cfg.executor.decode_chunk = 2
        cfg.executor.prefill_buckets = [32]
        cfg.model.name = "llama3-tiny"
        cfg.model.max_seq_len = 128
        cfg.executor.mesh.enabled = True
        cfg.executor.mesh.shape = {"dp": 2, "tp": 4}
        engine = build_engine(cfg, warmup=False, enable_metrics=False)
        assert engine.executor.mesh is not None
        assert engine.executor.dp_shards == 2
        assert engine.allocator.dp_shards == 2
        res = run_requests(engine, [dict(id="x", prompt="hi")])[0]
        assert res.finish_reason in ("eos", "length")
        engine.stop()

    def test_off_switch_builds_single_chip(self):
        """mesh.enabled=false (default) + no legacy tpu.mesh_shape →
        no mesh object at all: the exact single-chip executor."""
        from llmq_tpu.engine.builder import build_engine

        cfg = default_config()
        cfg.executor.backend = "jax"
        cfg.executor.max_batch_size = 2
        cfg.executor.kv_pages = 33
        cfg.executor.decode_chunk = 2
        cfg.executor.prefill_buckets = [32]
        cfg.model.name = "llama3-tiny"
        cfg.model.max_seq_len = 128
        engine = build_engine(cfg, warmup=False, enable_metrics=False)
        assert engine.executor.mesh is None
        assert engine.executor.dp_shards == 1
        assert engine.allocator.dp_shards == 1
        engine.stop()

    def test_legacy_tpu_mesh_shape_still_wires(self):
        from llmq_tpu.engine.builder import build_engine

        cfg = default_config()
        cfg.executor.backend = "jax"
        cfg.executor.max_batch_size = 2
        cfg.executor.kv_pages = 32
        cfg.executor.decode_chunk = 2
        cfg.executor.prefill_buckets = [32]
        cfg.model.name = "llama3-tiny"
        cfg.model.max_seq_len = 128
        cfg.tpu.mesh_shape = {"tp": 8}
        engine = build_engine(cfg, warmup=False, enable_metrics=False)
        assert engine.executor.mesh is not None
        engine.stop()


# -- demotion economics v2 (ROADMAP 4c satellite) ------------------------------


class TestDemotionEconomics:
    def test_hot_conversation_outlives_cold_under_pressure(self):
        """A conversation with a measured saved-prefill rate outlives a
        cold (but more recently used) one when pool pressure reclaims
        a pin — value ranking, not recency."""
        from llmq_tpu.core.config import KVTieringConfig
        from llmq_tpu.engine.engine import _ConvKV
        from llmq_tpu.observability.usage import (RequestUsage,
                                                  get_usage_ledger,
                                                  reset_usage)
        from llmq_tpu.engine.executor import EchoExecutor

        reset_usage()
        led = get_usage_ledger()
        led.reconfigure(enabled=True)
        try:
            tok = ByteTokenizer()
            ex = EchoExecutor(batch_size=2, page_size=8, num_pages=32,
                              max_pages_per_seq=8, eos_id=tok.eos_id)
            eng = InferenceEngine(
                ex, tok, enable_metrics=False, name="econ",
                kv_tiering=KVTieringConfig(enabled=True))
            assert eng._tiering.eviction_policy == "saved_rate"
            # "hot" keeps earning saved-prefill credit; "cold" never
            # did — but was touched MORE recently.
            u = RequestUsage()
            u.saved_prefill_device_s = 2.0
            led.finalize("r-hot", u, tenant="t", priority="normal",
                         engine="econ", conversation="hot", tokens=4)
            for cid, ts in (("hot", 10.0), ("cold", 99.0)):
                pages = eng.allocator.alloc(2)
                bt = np.zeros(eng.spec.max_pages_per_seq, np.int32)
                bt[:2] = pages
                eng._conv_cache[cid] = _ConvKV(
                    pages=pages, block_table=bt, length=8,
                    last_used=ts, tokens=list(range(8)))
                eng.allocator.pin(cid, pages)
            assert eng._reclaim_idle_conversation()
            assert "hot" in eng._conv_cache       # survived
            assert "cold" not in eng._conv_cache  # evicted first
            eng.stop()
        finally:
            reset_usage()

    def test_lru_policy_restores_recency(self):
        from llmq_tpu.core.config import KVTieringConfig

        cfg = KVTieringConfig(enabled=True, eviction_policy="lru")
        assert cfg.eviction_policy == "lru"
        with pytest.raises(ValueError, match="eviction_policy"):
            KVTieringConfig(eviction_policy="mru")

    def test_plane_spill_ranks_by_saved_rate(self):
        """Host→store spill picks the lowest-value entry, not the
        least recent, when the ledger has signal."""
        from llmq_tpu.core.clock import FakeClock
        from llmq_tpu.core.config import KVTieringConfig
        from llmq_tpu.conversation.persistence import InMemoryStore
        from llmq_tpu.observability.usage import (RequestUsage,
                                                  get_usage_ledger,
                                                  reset_usage)
        from llmq_tpu.tiering import KVTieringPlane

        class FakeKVExec:
            def kv_page_spec(self):
                return [((2, 4, 8), np.dtype(np.float32))]

            def export_kv_pages(self, pages):
                return [np.stack([np.full((2, 4, 8), float(p),
                                          np.float32) for p in pages],
                                 axis=1)]

            def import_kv_pages(self, pages, leaves):
                pass

        reset_usage()
        led = get_usage_ledger()
        led.reconfigure(enabled=True)
        try:
            clock = FakeClock()
            plane = KVTieringPlane(
                KVTieringConfig(enabled=True, host_max_conversations=2),
                "econplane", FakeKVExec(), clock=clock, metrics=False)
            plane.store = InMemoryStore()
            assert plane.eviction_policy == "saved_rate"
            u = RequestUsage()
            u.saved_prefill_device_s = 3.0
            led.finalize("r-hot2", u, tenant="t", priority="normal",
                         engine="econplane", conversation="hot",
                         tokens=4)
            # "hot" is demoted FIRST (oldest last_used) — pure LRU
            # would spill it; value ranking spills the cold ones.
            for cid in ("hot", "cold", "third"):
                plane.demote(cid, [1], list(range(8)), 8, None)
                assert wait_until(
                    lambda c=cid: plane._entries[c].ready.is_set()
                    or plane._entries[c].spilling)
                clock.advance(5.0)
            assert wait_until(lambda: plane.counts()["store"] == 1
                              and plane.counts()["host"] == 2)
            with plane._mu:
                assert plane._entries["hot"].tier == "host"
                assert plane._entries["cold"].tier == "store"
            plane.stop()
        finally:
            reset_usage()


class TestDpAllocLadder:
    def test_cross_universe_fallback_beats_shedding(self):
        """A full universe with room elsewhere must take the
        cross-universe pages — NOT destroy pinned conversation KV or
        preempt anything (bounded non-locality is the cheapest rung)."""
        from llmq_tpu.engine.engine import (GenHandle, _ConvKV,
                                            _Sequence)
        from llmq_tpu.engine.executor import EchoExecutor

        tok = ByteTokenizer()
        ex = EchoExecutor(batch_size=4, page_size=8, num_pages=32,
                          max_pages_per_seq=8, eos_id=tok.eos_id)
        ex.dp_shards = 2
        eng = InferenceEngine(ex, tok, enable_metrics=False,
                              name="ladder")
        assert eng.allocator.dp_shards == 2
        # A pinned conversation in universe 1 — the ladder's shed
        # victim if it ever gets that far.
        pin = eng.allocator.alloc(2, shard=1)
        bt = np.zeros(eng.spec.max_pages_per_seq, np.int32)
        bt[:2] = pin
        eng._conv_cache["pinme"] = _ConvKV(
            pages=pin, block_table=bt, length=8, last_used=0.0,
            tokens=list(range(8)))
        eng.allocator.pin("pinme", pin)
        # Exhaust universe 0 entirely.
        assert eng.allocator.alloc(
            eng.allocator.available(shard=0), shard=0) is not None
        req = GenRequest(id="x", prompt="hi")
        seq = _Sequence(req, GenHandle(req), 0,
                        eng.spec.max_pages_per_seq)
        got = eng._alloc_pages(2, seq, shard=0)
        assert got is not None
        assert all(eng.allocator.shard_of(p) == 1 for p in got)
        assert "pinme" in eng._conv_cache     # no shedding happened
        eng.stop()


# -- 8B tp4 AOT lowering (extends tests/test_scale.py's flagship set) ----------


_AOT_8B_TP4 = r"""
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass
import jax.numpy as jnp
from llmq_tpu.models.llama import (forward_decode, get_config,
                                   init_kv_pages, init_params)
from llmq_tpu.parallel.mesh import make_mesh
from llmq_tpu.parallel.sharding import (batch_sharding,
                                        kv_cache_shardings,
                                        param_shardings)

assert len(jax.devices()) == 8, len(jax.devices())
# BASELINE config #2: llama3-8b bf16 on v5e-8, tp=4 over a dp2 x tp4
# mesh (8 GQA KV heads shard 4 ways; dp splits the page axis).
cfg = get_config("llama3-8b", max_seq_len=8192)
mesh = make_mesh({{"dp": 2, "tp": 4}})
B, page_size = 8, 128
mpps = cfg.max_seq_len // page_size
num_pages = B * mpps + 2   # even → dp-divisible

abs_params = jax.eval_shape(
    lambda: init_params(jax.random.PRNGKey(0), cfg))
abs_cache = jax.eval_shape(lambda: init_kv_pages(cfg, num_pages,
                                                 page_size))

def with_sharding(avals, shardings):
    return jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        avals, shardings)

a_params = with_sharding(abs_params, param_shardings(cfg, mesh))
a_cache = with_sharding(dict(abs_cache),
                        dict(kv_cache_shardings(cfg, mesh,
                                                num_pages=num_pages)))
a_tok = jax.ShapeDtypeStruct((B,), jnp.int32,
                             sharding=batch_sharding(mesh, 1))
a_pos = jax.ShapeDtypeStruct((B,), jnp.int32,
                             sharding=batch_sharding(mesh, 1))
a_bt = jax.ShapeDtypeStruct((B, mpps), jnp.int32,
                            sharding=batch_sharding(mesh, 2))

f = jax.jit(lambda p, t, pos, c, bt: forward_decode(p, cfg, t, pos, c, bt))
compiled = f.lower(a_params, a_tok, a_pos, a_cache, a_bt).compile()
mem = compiled.memory_analysis()
per_dev_gb = mem.argument_size_in_bytes / 1e9
assert per_dev_gb < 16.0 * 0.9, f"{{per_dev_gb:.1f}} GB/chip"

# Export-cache key identity under the mesh-aware cache: the REAL key
# function over the flagship geometry (abstract trees carry shapes +
# dtypes, which is all the key hashes).
from types import SimpleNamespace
from llmq_tpu.engine.executor import ExecutorSpec, JaxExecutor

def key_for(mesh_, dp_shards, cache):
    stub = SimpleNamespace(
        model_cfg=cfg,
        spec=ExecutorSpec(B, page_size, num_pages, mpps, 2),
        chunk_size=16, prefill_batch=4, prefill_buckets=[512],
        _top_k=0, _top_p=1.0, mixed_prefill_slices=0,
        mixed_slice_tokens=0, ragged_attention=False,
        _ragged_buf=0, _ragged_qblk=0,
        verify_draft_k=0, _spec_device_sampling=True, mesh=mesh_,
        dp_shards=dp_shards, params=abs_params, cache=cache)
    return JaxExecutor._export_cache_key(stub)

k_mesh = key_for(mesh, 2, a_cache)
k_single = key_for(None, 1, dict(abs_cache))
k_tp8 = key_for(make_mesh({{"tp": 8}}), 1, dict(abs_cache))
assert len({{k_mesh, k_single, k_tp8}}) == 3, (k_mesh, k_single, k_tp8)
assert k_mesh == key_for(mesh, 2, a_cache)
print(f"AOT8B OK {{per_dev_gb:.2f}} GB/chip", flush=True)
"""


@pytest.mark.skipif(os.environ.get("LLMQ_SKIP_MULTIPROC") == "1",
                    reason="multi-process test disabled")
def test_8b_tp4_aot_lowering_and_mesh_cache_key():
    """8B bf16 at dp2×tp4 AOT-lowers from ShapeDtypeStructs on the
    8-virtual-device CPU mesh, fits a 16 GB v5e chip per-device, and
    the export-cache key separates mesh/single-chip/re-geometried
    artifacts (ISSUE 15 acceptance)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = _AOT_8B_TP4.format(repo=repo)
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))
           and k not in ("PYTHONPATH", "PYTHONSTARTUP")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    p = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=600)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "AOT8B OK" in p.stdout, p.stdout
