"""Metrics-cardinality guard (ISSUE 6 satellite).

Prometheus label sets must be BOUNDED: a label that ever carries a
per-request value (request id, UUID, conversation id) grows one time
series per request and kills the scrape. This suite walks every
collector in the registry and enforces the contract declared next to
the families themselves (``metrics.registry.LABEL_CONTRACT``):

- every label name any family uses must be declared in the contract;
- labels declared as closed enums may only ever carry values from the
  enum;
- config/hardware-bounded labels (engine, endpoint, chip, program …)
  must never carry values that look like request/trace identifiers.

Adding a family with a new label without extending the contract fails
here by design — the reviewer then decides whether the set is bounded.
"""

from __future__ import annotations

import re

from llmq_tpu.metrics.registry import (LABEL_CONTRACT, REGISTRY,
                                       get_metrics)

#: Values that smell like per-request identifiers: UUIDs, long hex,
#: long digit runs (message ids, timestamps).
_ID_RX = re.compile(
    r"^[0-9a-f]{8}-[0-9a-f]{4}-[0-9a-f]{4}-[0-9a-f]{4}-[0-9a-f]{12}$"
    r"|^[0-9a-f]{12,}$"
    r"|^\d{6,}$",
    re.IGNORECASE)

#: Window labels ("5m", "1h", "90s") — bounded by the configured
#: window list, validated by shape.
_WINDOW_RX = re.compile(r"^\d{1,5}[smh]$")


def _families():
    get_metrics()   # ensure every family exists
    return list(REGISTRY.collect())


class TestLabelContract:
    def test_every_label_is_declared(self):
        undeclared = {}
        for fam in _families():
            for sample in fam.samples:
                unknown = set(sample.labels) - set(LABEL_CONTRACT)
                if unknown:
                    undeclared.setdefault(fam.name, set()).update(unknown)
        # Histograms add "le" internally; it is prometheus-bounded.
        undeclared = {k: v - {"le"} for k, v in undeclared.items()}
        undeclared = {k: v for k, v in undeclared.items() if v}
        assert not undeclared, (
            f"families using labels absent from LABEL_CONTRACT: "
            f"{undeclared} — declare them (enum or bounded-by-config) "
            f"in metrics/registry.py")

    def test_enum_labels_stay_within_their_enum(self):
        violations = []
        for fam in _families():
            for sample in fam.samples:
                for label, value in sample.labels.items():
                    allowed = LABEL_CONTRACT.get(label)
                    if isinstance(allowed, frozenset) \
                            and value not in allowed:
                        violations.append((fam.name, label, value))
        assert not violations, (
            f"label values outside their declared enum: {violations}")

    def test_bounded_labels_never_carry_request_ids(self):
        violations = []
        for fam in _families():
            for sample in fam.samples:
                for label, value in sample.labels.items():
                    if label == "le" or isinstance(
                            LABEL_CONTRACT.get(label), frozenset):
                        continue
                    if label == "window":
                        if not _WINDOW_RX.match(value):
                            violations.append((fam.name, label, value))
                        continue
                    if _ID_RX.match(value) or len(value) > 128:
                        violations.append((fam.name, label, value))
        assert not violations, (
            f"id-shaped values on bounded labels (unbounded "
            f"cardinality): {violations}")

    def test_guard_actually_rejects_a_request_id(self):
        # The detector itself must catch the canonical mistakes, or
        # the two tests above are vacuous.
        assert _ID_RX.match("8c94e42e-6f3f-4a73-a18f-000000000001")
        assert _ID_RX.match("a3f9c2e4b1d05876")
        assert _ID_RX.match("1785755681")
        assert not _ID_RX.match("engine0")
        assert not _ID_RX.match("prefill_b512")
        assert not _ID_RX.match("tpu-host-a:8080")


class TestTenantLabelBound:
    """The ``tenant`` label is CLIENT-supplied — the one label in the
    registry an external caller can try to spray. The usage ledger must
    keep it bounded: at most ``max_tenants`` distinct series, overflow
    and id-shaped values collapsing to "other"."""

    def test_tenant_spray_collapses_to_other(self):
        from llmq_tpu.observability.usage import (RequestUsage,
                                                  get_usage_ledger,
                                                  reset_usage)
        reset_usage()
        led = get_usage_ledger()
        led.reconfigure(enabled=True, max_tenants=4)
        try:
            # 4 legit tenants, then a spray of 50 uuid-ish ids.
            sprayed = [f"cardtenant-{i}" for i in range(4)] + [
                f"{i:032x}"[:12] + "deadbeef" for i in range(50)]
            for i, t in enumerate(sprayed):
                ru = RequestUsage()
                ru.device_s = 0.001
                led.finalize(f"spray-{i}", ru, tenant=t,
                             priority="normal", engine="cardtest",
                             ok=True)
            led.metrics_enabled = True
            led.flush()
            seen = set()
            for fam in _families():
                if fam.name != "llm_queue_usage_device_seconds":
                    continue
                for sample in fam.samples:
                    t = sample.labels.get("tenant")
                    if t is not None and t.startswith(
                            ("cardtenant-", "other")) is False:
                        # Foreign tenants from other tests are fine;
                        # only THIS spray's ids must not appear.
                        assert "deadbeef" not in t, sample
                    if t is not None:
                        seen.add(t)
            assert {f"cardtenant-{i}" for i in range(4)} <= seen
            assert "other" in seen
            assert not any("deadbeef" in t for t in seen)
        finally:
            reset_usage()

    def test_ledger_enforces_bound_even_for_clean_names(self):
        from llmq_tpu.observability.usage import UsageLedger
        led = UsageLedger(max_tenants=2)
        labels = {led.tenant_label(f"team-{i}") for i in range(10)}
        assert labels == {"team-0", "team-1", "other"}

    def test_tenancy_fairness_families_share_the_bound(self):
        """The tenancy plane's per-tenant gauges (tenant_virtual_time,
        tenant_share_ratio, tenant_inflight) flush through the SAME
        first-come ``max_tenants`` mapping as the usage families — a
        tenant-id spray through the fair scheduler must collapse to
        "other", never mint a series per sprayed id."""
        from llmq_tpu import tenancy
        from llmq_tpu.core.config import TenancyConfig
        from llmq_tpu.metrics.registry import exposition
        from llmq_tpu.observability.usage import (get_usage_ledger,
                                                  reset_usage)
        reset_usage()
        get_usage_ledger().reconfigure(enabled=True, max_tenants=2)
        tenancy.reset_tenancy()
        reg = tenancy.configure_tenancy(TenancyConfig(enabled=True))
        sched = tenancy.FairScheduler(reg)
        tenancy.register_scheduler(sched)
        try:
            class _Msg:
                def __init__(self, i):
                    self.id = f"card-{i}"
                    self.tenant_id = f"sprayed-tenant-{i}"
                    self.content = "x" * 40
                    self.metadata = {}
            for i in range(20):
                m = _Msg(i)
                sched.on_push("normal", m, i + 1)
                assert sched.select("normal") == i + 1
                sched.note_pop(m)
                sched.note_finish(m)
            exp = exposition().decode()
            tenant_values = set()
            for fam in _families():
                if not fam.name.startswith("llm_queue_tenant_"):
                    continue
                for sample in fam.samples:
                    t = sample.labels.get("tenant")
                    if t is not None and t.startswith(
                            ("sprayed-tenant-", "other")):
                        tenant_values.add(t)
            # 2 first-come series + "other"; the other 18 sprayed ids
            # never appear (checked against the raw exposition too).
            assert "other" in tenant_values
            assert len(tenant_values) <= 3, tenant_values
            for i in range(2, 20):
                assert f'tenant="sprayed-tenant-{i}"' not in exp
        finally:
            tenancy.reset_tenancy()
            reset_usage()
