"""Token-budget mixed prefill+decode batching (docs/architecture.md
"Mixed step"): the fused scheduling path must be TOKEN-FOR-TOKEN
equivalent to the unfused one — across admission waves, preemption and
resume, and prefix-cache continuation prefill — while honoring the
prefill token budget per iteration and populating the stall
attribution metrics. ``mixed_batch.enabled: false`` is a hard
off-switch: the executor must never see a mixed dispatch."""

import jax
import pytest

from llmq_tpu.core.config import MixedBatchConfig, PrefixCacheConfig
from llmq_tpu.core.types import Priority
from llmq_tpu.engine.engine import GenRequest, InferenceEngine
from llmq_tpu.engine.executor import EchoExecutor, JaxExecutor
from llmq_tpu.engine.tokenizer import ByteTokenizer
from llmq_tpu.models.llama import get_config, init_params


def mixed_cfg(enabled=True, budget=16, slices=2):
    return MixedBatchConfig(enabled=enabled, prefill_token_budget=budget,
                            max_slices=slices)


def make_echo_engine(mixed=None, slots=4, chunk=4, **kw):
    tok = ByteTokenizer()
    ex = EchoExecutor(batch_size=slots, page_size=8, num_pages=256,
                      max_pages_per_seq=16, eos_id=tok.eos_id,
                      chunk_size=chunk, mixed_prefill_slices=2,
                      mixed_slice_tokens=8)
    eng = InferenceEngine(ex, tok, enable_metrics=False,
                          max_decode_steps=64, mixed_batch=mixed, **kw)
    return eng, ex


WAVE = [
    ("hello world this is a long prompt " * 3, Priority.NORMAL),
    ("short", Priority.REALTIME),
    ("medium sized prompt here", Priority.LOW),
    ("another quite long prompt for slicing " * 2, Priority.HIGH),
    ("fifth request", Priority.NORMAL),
    ("sixth one goes last", Priority.LOW),
]


def drive_wave(eng, wave=WAVE, conv=None, steps_between=2,
               max_new=40):
    """Submit a wave with interleaved scheduling; returns handles."""
    handles = []
    for i, (prompt, prio) in enumerate(wave):
        handles.append(eng.submit(GenRequest(
            id=f"r{i}", prompt=prompt, priority=prio,
            conversation_id=(conv[i] if conv else ""),
            max_new_tokens=max_new)))
        for _ in range(steps_between):
            eng.step()
    eng.run_until_idle()
    return handles


class TestEchoEquivalence:
    def test_admission_wave_streams_identical(self):
        def run(mixed):
            eng, _ = make_echo_engine(mixed)
            handles = drive_wave(eng)
            return [h.result.tokens for h in handles], eng.get_stats()

        on, s_on = run(mixed_cfg())
        off, s_off = run(None)
        assert on == off
        # The fused path actually ran (long prompts + active decode
        # rows force mixed iterations) and the unfused path never
        # tracked mixed state.
        assert s_on["mixed_batch"]["steps"] > 0
        assert s_on["mixed_batch"]["prefill_tokens"] > 0
        assert "mixed_batch" not in s_off

    def test_preemption_equivalence_single_slot(self):
        """Preemption/resume (slot handoff + page-release rebuild)
        under mixed batching: per-request streams must not change."""
        def run(mixed):
            eng, _ = make_echo_engine(mixed, slots=1)
            low = eng.submit(GenRequest(
                id="low", prompt="background work " * 4,
                priority=Priority.LOW, max_new_tokens=48))
            for _ in range(6):
                eng.step()
            rt = eng.submit(GenRequest(
                id="rt", prompt="urgent realtime request",
                priority=Priority.REALTIME, max_new_tokens=8))
            eng.run_until_idle()
            return low.result.tokens, rt.result.tokens

        assert run(mixed_cfg()) == run(None)

    def test_conversation_continuation_equivalence(self):
        """Turn-2 continuation prefill over pinned conversation KV
        rides the mixed path identically."""
        def run(mixed):
            eng, _ = make_echo_engine(mixed)
            out = []
            for turn in range(3):
                handles = drive_wave(
                    eng,
                    wave=[(f"turn {turn} says something longish "
                           f"{'x' * (10 * turn)}", Priority.NORMAL)] * 3,
                    conv=[f"c{i}" for i in range(3)],
                    max_new=24)
                out.append([h.result.tokens for h in handles])
            return out

        assert run(mixed_cfg()) == run(None)

    def test_budget_honored_and_slices_capped(self):
        """Every mixed dispatch fuses ≤ prefill_token_budget tokens
        across ≤ max_slices slices, each ≤ the executor slice width."""
        eng, ex = make_echo_engine(mixed_cfg(budget=16, slices=2))
        seen = []
        orig = ex.mixed_chunk

        def spy(tokens, positions, block_tables, temps, budgets, pf):
            seen.append([(slot, len(t)) for slot, t, *_ in pf])
            return orig(tokens, positions, block_tables, temps,
                        budgets, pf)

        ex.mixed_chunk = spy
        drive_wave(eng)
        assert seen, "mixed path never dispatched"
        for pf in seen:
            assert 1 <= len(pf) <= 2
            assert sum(n for _, n in pf) <= 16
            assert all(n <= ex.mixed_slice_tokens for _, n in pf)

    def test_off_switch_no_mixed_calls(self):
        """enabled=false → the executor NEVER sees a mixed dispatch,
        even though it supports one (hard off-switch)."""
        eng, ex = make_echo_engine(mixed_cfg(enabled=False))

        def boom(*a, **kw):
            raise AssertionError("mixed dispatch with mixed_batch off")

        ex.mixed_chunk = boom
        handles = drive_wave(eng)
        assert all(h.result.finish_reason in ("eos", "length")
                   for h in handles)

    def test_cancellation_mid_prefill(self):
        """A cancelled mid-prefill sequence is reaped from the mixed
        path without leaking its slot or pages."""
        eng, ex = make_echo_engine(mixed_cfg())
        keep = eng.submit(GenRequest(id="keep", prompt="steady " * 10,
                                     max_new_tokens=32))
        for _ in range(4):
            eng.step()
        doomed = eng.submit(GenRequest(
            id="doomed", prompt="a very long prompt " * 8,
            priority=Priority.LOW, max_new_tokens=32))
        eng.step()
        doomed.cancel()
        eng.run_until_idle()
        assert doomed.result.finish_reason == "cancelled"
        assert keep.result.finish_reason in ("eos", "length")
        assert eng.allocator.used() == eng.allocator.pinned_pages()
        assert all(s is None for s in eng._slots)


class TestPrefillRateEstimator:
    def test_engine_learns_and_feeds_scheduler(self):
        from llmq_tpu.scheduling.resource_scheduler import (
            ResourceScheduler)

        sched = ResourceScheduler()
        eng, _ = make_echo_engine(mixed_cfg())
        eng.on_prefill_observed = sched.observe_prefill
        drive_wave(eng)
        assert eng.prefill_tps_ewma and eng.prefill_tps_ewma > 0
        stats = sched.get_stats()
        assert stats["prefill_observations"] > 0
        assert stats["prefill_tokens_per_s"] > 0
        eta = sched.prefill_eta_ms(100)
        assert eta is not None and eta >= 0
        # Stall attribution populated engine-side too.
        s = eng.get_stats()
        assert s["prefill_stall_events"] > 0
        assert s["prefill_stall_ms_total"] >= 0

    def test_prefill_eta_before_observations(self):
        from llmq_tpu.scheduling.resource_scheduler import (
            ResourceScheduler)

        sched = ResourceScheduler()
        assert sched.prefill_eta_ms(100) is None
        sched.observe_prefill(0, 1.0)          # ignored
        sched.observe_prefill(100, 0.0)        # ignored
        assert sched.get_stats()["prefill_observations"] == 0


class TestStallMetrics:
    def test_prefill_stall_histogram_populated(self):
        """With metrics ON, mixed iterations observe the
        llm_queue_prefill_stall_ms histogram and set the occupancy
        gauges (the CI smoke's assertion)."""
        from llmq_tpu.metrics.registry import exposition, get_metrics

        get_metrics()
        tok = ByteTokenizer()
        ex = EchoExecutor(batch_size=4, page_size=8, num_pages=256,
                          max_pages_per_seq=16, eos_id=tok.eos_id,
                          chunk_size=4, mixed_prefill_slices=2,
                          mixed_slice_tokens=8)
        eng = InferenceEngine(ex, tok, enable_metrics=True,
                              name="mixedtest", max_decode_steps=64,
                              mixed_batch=mixed_cfg())
        drive_wave(eng)
        exp = exposition().decode()
        assert "llm_queue_prefill_stall_ms" in exp
        assert ('llm_queue_prefill_stall_ms_count{engine="mixedtest",'
                'path="mixed"}') in exp
        assert "llm_queue_mixed_step_prefill_tokens" in exp
        assert "llm_queue_mixed_budget_utilization" in exp


# -- CPU-mode JAX equivalence --------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    cfg = get_config("llama3-tiny", max_seq_len=256, vocab_size=512)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def make_jax_engine(tiny_model, mixed, *, slots=3, prefix_cache=None,
                    max_decode_steps=16):
    cfg, params = tiny_model
    tok = ByteTokenizer()
    ex = JaxExecutor(cfg, params, batch_size=slots, page_size=8,
                     num_pages=96, prefill_buckets=[16, 64],
                     eos_id=tok.eos_id, chunk_size=4,
                     mixed_prefill_slices=2, mixed_slice_tokens=8)
    return InferenceEngine(ex, tok, enable_metrics=False,
                           max_decode_steps=max_decode_steps,
                           prefix_cache=prefix_cache, mixed_batch=mixed)


class TestJaxEquivalence:
    def test_wave_with_preemption_streams_identical(self, tiny_model):
        """Greedy CPU-mode JAX: admission waves (slices spanning
        iterations) + a realtime arrival that preempts — identical
        per-request token streams with mixed batching on vs off."""
        def run(mixed):
            eng = make_jax_engine(tiny_model, mixed, slots=2)
            handles = []
            wave = [("a long prompt that needs slicing into chunks",
                     Priority.LOW),
                    ("second prompt arrives", Priority.NORMAL),
                    ("urgent!", Priority.REALTIME),
                    ("fourth one trails behind the others",
                     Priority.HIGH)]
            for i, (p, prio) in enumerate(wave):
                handles.append(eng.submit(GenRequest(
                    id=f"j{i}", prompt=p, priority=prio,
                    max_new_tokens=10)))
                eng.step()
                eng.step()
            eng.run_until_idle()
            return ([h.result.tokens for h in handles],
                    eng.get_stats())

        on, s_on = run(mixed_cfg())
        off, _ = run(None)
        assert s_on["mixed_batch"]["steps"] > 0, "fused path never ran"
        assert on == off

    def test_prefix_cache_continuation_equivalence(self, tiny_model):
        """Multi-turn conversations over the radix prefix cache:
        continuation prefill (cached KV + tail slices) must decode
        identically through the mixed path."""
        def run(mixed):
            eng = make_jax_engine(
                tiny_model, mixed,
                prefix_cache=PrefixCacheConfig(enabled=True))
            out = []
            for turn in range(2):
                handles = []
                for c in range(3):
                    handles.append(eng.submit(GenRequest(
                        id=f"t{turn}c{c}",
                        prompt=f" turn {turn} for conversation {c}",
                        conversation_id=f"conv{c}",
                        max_new_tokens=8)))
                    eng.step()
                eng.run_until_idle()
                out.append([h.result.tokens for h in handles])
            # Reuse actually happened on turn 2.
            assert eng.prefix_hits > 0 or any(
                h.result.cached_tokens > 0 for h in handles)
            return out

        assert run(mixed_cfg()) == run(None)

    def test_multi_chunk_generation_through_mixed(self, tiny_model):
        """A generation spanning several chunks while later arrivals
        prefill through the fused program runs to full length."""
        eng = make_jax_engine(tiny_model, mixed_cfg(),
                              max_decode_steps=24)
        first = eng.submit(GenRequest(id="first", prompt="go",
                                      max_new_tokens=24))
        for _ in range(4):
            eng.step()
        later = eng.submit(GenRequest(
            id="later", prompt="a later long prompt to slice up",
            max_new_tokens=6))
        eng.run_until_idle()
        assert first.result.finish_reason in ("eos", "length")
        assert later.result.finish_reason in ("eos", "length")
        if first.result.finish_reason == "length":
            assert len(first.result.tokens) == 24
        assert eng.allocator.used() == eng.allocator.pinned_pages()
