"""Model-layer tests: llama forward correctness, paged KV semantics,
sampling, checkpointing. Runs on CPU in f32 for exact-ish numerics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmq_tpu.models.llama import (
    forward_decode,
    forward_prefill,
    get_config,
    init_kv_pages,
    init_params,
    llama3_70b,
    llama3_8b,
    llama3_tiny,
    loss_fn,
    param_count,
)
from llmq_tpu.ops.attention import causal_prefill_attention, paged_decode_attention
from llmq_tpu.ops.norms import rms_norm
from llmq_tpu.ops.rope import apply_rope, rope_cos_sin
from llmq_tpu.ops.sampling import greedy, sample_token

CFG = llama3_tiny(dtype=jnp.float32)
PAGE, NPAGES, MAXP = 4, 64, 8


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def fresh_cache():
    return init_kv_pages(CFG, NPAGES, PAGE, dtype=jnp.float32)


def tables(*page_lists):
    bt = np.zeros((len(page_lists), MAXP), np.int32)
    for i, pages in enumerate(page_lists):
        bt[i, :len(pages)] = pages
    return jnp.asarray(bt)


class TestConfigs:
    def test_known_architectures(self):
        c8 = llama3_8b()
        assert (c8.dim, c8.n_layers, c8.n_heads, c8.n_kv_heads) == (4096, 32, 32, 8)
        c70 = llama3_70b()
        assert (c70.dim, c70.n_layers, c70.n_heads) == (8192, 80, 64)
        assert get_config("llama3-tiny").name == "llama3-tiny"
        with pytest.raises(ValueError):
            get_config("llama4-900b")

    def test_param_count_tiny(self, params):
        assert param_count(params) == 426_624


class TestForward:
    def test_prefill_decode_equivalence(self, params):
        """The core correctness invariant: decoding token t with cached
        prefix must produce the same logits as full prefill at t."""
        B, T = 2, 10
        key = jax.random.PRNGKey(1)
        tokens = jax.random.randint(key, (B, T), 0, CFG.vocab_size)
        positions = jnp.broadcast_to(jnp.arange(T), (B, T))
        bt = tables([1, 2, 3], [4, 5, 6])
        full_logits, _ = forward_prefill(
            params, CFG, tokens, positions, jnp.array([T, T]), fresh_cache(), bt)
        # Prefill 6, then decode tokens 6..9 one at a time.
        cache = fresh_cache()
        _, cache = forward_prefill(
            params, CFG, tokens[:, :6], positions[:, :6], jnp.array([6, 6]),
            cache, bt)
        for t in range(6, T):
            step_logits, cache = forward_decode(
                params, CFG, tokens[:, t], jnp.array([t, t]), cache, bt)
            np.testing.assert_allclose(
                step_logits, full_logits[:, t], rtol=2e-4, atol=2e-4)

    def test_ragged_prefill_padding_isolated(self, params):
        """A short sequence padded inside a batch must produce the same
        logits as alone — page 0 absorbs padding garbage."""
        key = jax.random.PRNGKey(2)
        toks = jax.random.randint(key, (1, 5), 0, CFG.vocab_size)
        pos5 = jnp.arange(5)[None, :]
        solo, _ = forward_prefill(params, CFG, toks, pos5, jnp.array([5]),
                                  fresh_cache(), tables([1, 2]))
        batch_toks = jnp.concatenate(
            [jnp.pad(toks, ((0, 0), (0, 3))),
             jax.random.randint(key, (1, 8), 0, CFG.vocab_size)])
        pos8 = jnp.broadcast_to(jnp.arange(8), (2, 8))
        batched, _ = forward_prefill(
            params, CFG, batch_toks, pos8, jnp.array([5, 8]),
            fresh_cache(), tables([1, 2], [3, 4]))
        np.testing.assert_allclose(batched[0, :5], solo[0], rtol=2e-4, atol=2e-4)

    def test_conversation_continuation(self, params):
        """Turn 2 prefill over retained pages == one long prefill
        (BASELINE config #3: KV reuse across turns)."""
        key = jax.random.PRNGKey(3)
        toks = jax.random.randint(key, (1, 8), 0, CFG.vocab_size)
        pos = jnp.arange(8)[None, :]
        bt = tables([1, 2])
        full, _ = forward_prefill(params, CFG, toks, pos, jnp.array([8]),
                                  fresh_cache(), bt)
        cache = fresh_cache()
        _, cache = forward_prefill(params, CFG, toks[:, :4], pos[:, :4],
                                   jnp.array([4]), cache, bt)
        cont, _ = forward_prefill(params, CFG, toks[:, 4:], pos[:, 4:],
                                  jnp.array([4]), cache, bt)
        np.testing.assert_allclose(cont[0], full[0, 4:], rtol=2e-4, atol=2e-4)

    def test_pages_isolate_sequences(self, params):
        """Two sequences with disjoint pages must not see each other."""
        key = jax.random.PRNGKey(4)
        toks = jax.random.randint(key, (2, 6), 0, CFG.vocab_size)
        pos = jnp.broadcast_to(jnp.arange(6), (2, 6))
        together, _ = forward_prefill(
            params, CFG, toks, pos, jnp.array([6, 6]), fresh_cache(),
            tables([1, 2], [3, 4]))
        alone0, _ = forward_prefill(
            params, CFG, toks[:1], pos[:1], jnp.array([6]), fresh_cache(),
            tables([1, 2]))
        np.testing.assert_allclose(together[0], alone0[0], rtol=2e-4, atol=2e-4)

    def test_loss_and_grad_finite(self, params):
        toks = jax.random.randint(jax.random.PRNGKey(5), (2, 8), 0,
                                  CFG.vocab_size)
        bt = tables([1, 2], [3, 4])
        val, grads = jax.value_and_grad(loss_fn)(
            params, CFG, toks, fresh_cache(), bt)
        assert jnp.isfinite(val)
        assert all(bool(jnp.isfinite(g).all())
                   for g in jax.tree_util.tree_leaves(grads))


class TestOps:
    def test_rms_norm_unit_scale(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 64))
        out = rms_norm(x, jnp.ones(64))
        rms = jnp.sqrt(jnp.mean(out ** 2, axis=-1))
        np.testing.assert_allclose(rms, 1.0, rtol=1e-3)

    def test_rope_preserves_norm_and_relativity(self):
        q = jax.random.normal(jax.random.PRNGKey(1), (1, 4, 2, 8))
        pos = jnp.arange(4)[None, :]
        cos, sin = rope_cos_sin(pos, 8)
        q_rot = apply_rope(q, cos, sin)
        np.testing.assert_allclose(
            jnp.linalg.norm(q_rot, axis=-1), jnp.linalg.norm(q, axis=-1),
            rtol=1e-5)
        # Relative property: <R(p)q, R(p+k)v> depends only on k.
        v = jax.random.normal(jax.random.PRNGKey(2), (1, 4, 2, 8))
        v_rot = apply_rope(v, cos, sin)
        d01 = jnp.sum(q_rot[0, 0] * v_rot[0, 1])
        cos2, sin2 = rope_cos_sin(pos + 5, 8)
        q_rot2 = apply_rope(q, cos2, sin2)
        v_rot2 = apply_rope(v, cos2, sin2)
        d01_shift = jnp.sum(q_rot2[0, 0] * v_rot2[0, 1])
        np.testing.assert_allclose(d01, d01_shift, rtol=1e-4, atol=1e-5)

    def test_paged_decode_matches_dense(self):
        """paged_decode_attention == dense attention over the gathered
        history."""
        key = jax.random.PRNGKey(3)
        B, H, HKV, D, page = 2, 4, 2, 8, 4
        q = jax.random.normal(key, (B, H, D))
        k_pages = jax.random.normal(jax.random.PRNGKey(4), (16, page, HKV, D))
        v_pages = jax.random.normal(jax.random.PRNGKey(5), (16, page, HKV, D))
        bt = jnp.array([[1, 2, 0, 0], [3, 4, 5, 0]])
        seq_lens = jnp.array([6, 11])
        out = paged_decode_attention(q, k_pages, v_pages, bt, seq_lens)
        # Dense reference for row 1:
        k_hist = k_pages[bt[1]].reshape(-1, HKV, D)[:11]
        v_hist = v_pages[bt[1]].reshape(-1, HKV, D)[:11]
        attn = causal_prefill_attention(
            q[1][None, None], k_hist[None], v_hist[None], q_offset=10)
        np.testing.assert_allclose(out[1], attn[0, 0], rtol=1e-5, atol=1e-5)


class TestSampling:
    def test_greedy(self):
        logits = jnp.array([[0.1, 5.0, 0.2], [3.0, 0.0, 0.1]])
        np.testing.assert_array_equal(greedy(logits), [1, 0])

    def test_temperature_zero_is_greedy(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (4, 100))
        out = sample_token(logits, jax.random.PRNGKey(1), temperature=0.0)
        np.testing.assert_array_equal(out, greedy(logits))

    def test_top_k_restricts_support(self):
        logits = jnp.array([[10.0, 9.0, -50.0, -50.0]])
        for i in range(20):
            tok = sample_token(logits, jax.random.PRNGKey(i),
                               temperature=1.0, top_k=2)
            assert int(tok[0]) in (0, 1)

    def test_top_p_keeps_head(self):
        logits = jnp.log(jnp.array([[0.6, 0.3, 0.05, 0.05]]))
        for i in range(20):
            tok = sample_token(logits, jax.random.PRNGKey(i),
                               temperature=1.0, top_p=0.7)
            assert int(tok[0]) in (0, 1)

    def test_per_sequence_temperature(self):
        logits = jnp.stack([jnp.array([5.0, 0.0]), jnp.array([5.0, 0.0])])
        out = sample_token(logits, jax.random.PRNGKey(0),
                           temperature=jnp.array([0.0, 1.0]))
        assert int(out[0]) == 0  # greedy row


class TestCheckpoint:
    def test_roundtrip(self, params, tmp_path):
        from llmq_tpu.models.checkpoint import load_checkpoint, save_checkpoint

        path = str(tmp_path / "ckpt")
        save_checkpoint(path, params)
        restored = load_checkpoint(path, template=params)
        for a, b in zip(jax.tree_util.tree_leaves(params),
                        jax.tree_util.tree_leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
