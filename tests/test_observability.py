"""Request-lifecycle trace plane (llmq_tpu/observability/,
docs/observability.md): traceparent propagation, flight-recorder
ring/SLA retention, stage histograms, Chrome export, the REST trace
routes, structured log context — and the overhead guard that keeps the
trace plane under 3 % of an echo-engine request."""

from __future__ import annotations

import json
import logging
import threading
import time

import pytest

from llmq_tpu import observability
from llmq_tpu.api.server import ApiServer
from llmq_tpu.core.config import ObservabilityConfig, default_config
from llmq_tpu.core.types import Message
from llmq_tpu.engine import ByteTokenizer, EchoExecutor, InferenceEngine
from llmq_tpu.observability import (FlightRecorder, chrome_trace,
                                    make_traceparent, parse_traceparent,
                                    trace_id_for)
from llmq_tpu.utils.logging import (ConsoleFormatter, JsonFormatter,
                                    bind_log_context, reset_log_context)


# -- W3C trace context --------------------------------------------------------

class TestTraceContext:
    def test_uuid_message_id_is_the_trace_id(self):
        rid = "8c94e42e-6f3f-4a73-a18f-000000000001"
        assert trace_id_for(rid) == rid.replace("-", "")

    def test_non_uuid_id_hashes_deterministically(self):
        a, b = trace_id_for("msg-7"), trace_id_for("msg-7")
        assert a == b and len(a) == 32
        assert trace_id_for("msg-8") != a

    def test_header_roundtrip(self):
        hdr = make_traceparent("8c94e42e-6f3f-4a73-a18f-000000000001")
        ctx = parse_traceparent(hdr)
        assert ctx is not None
        assert ctx.trace_id == "8c94e42e6f3f4a73a18f000000000001"
        assert len(ctx.span_id) == 16
        assert ctx.to_header() == hdr

    @pytest.mark.parametrize("bad", [
        None, "", "garbage", "00-short-span-01",
        "00-" + "0" * 32 + "-abcdefabcdef1234-01",   # all-zero trace id
        "ff-" + "a" * 32 + "-abcdefabcdef1234-01",   # forbidden version
    ])
    def test_malformed_headers_are_none(self, bad):
        assert parse_traceparent(bad) is None


# -- flight recorder ----------------------------------------------------------

def _finish_timeline(rec, rid, *, duration=0.01, fail=False, t0=None):
    t0 = time.time() if t0 is None else t0
    rec.record(rid, "enqueued", ts=t0, priority="normal")
    rec.record(rid, "scheduled", ts=t0 + duration / 4)
    rec.record(rid, "first_token", ts=t0 + duration / 2)
    rec.record(rid, "failed" if fail else "completed",
               ts=t0 + duration, completion_tokens=5)


class TestFlightRecorder:
    def test_ring_eviction_is_bounded(self):
        rec = FlightRecorder(capacity=4, emit_metrics=False)
        for i in range(10):
            rec.record(f"r{i}", "enqueued")
        assert len(rec) == 4
        assert rec.get("r0") is None          # evicted
        assert rec.get("r9") is not None
        assert rec.get_stats()["dropped"] == 6

    def test_sla_breach_retained_after_ring_eviction(self):
        rec = FlightRecorder(capacity=2, sla_ms=50.0, emit_metrics=False)
        _finish_timeline(rec, "slow-1", duration=0.2)   # 200ms > 50ms
        for i in range(5):                               # flush the ring
            rec.record(f"noise{i}", "enqueued")
        tl = rec.get("slow-1")                           # from slow buffer
        assert tl is not None and tl.breached
        assert [t.request_id for t in rec.slow()] == ["slow-1"]
        assert rec.get_stats()["sla_breaches"] == 1

    def test_fast_requests_not_retained(self):
        rec = FlightRecorder(capacity=8, sla_ms=10_000.0,
                             emit_metrics=False)
        _finish_timeline(rec, "fast", duration=0.001)
        assert rec.slow() == []
        assert not rec.get("fast").breached

    def test_failed_requests_always_retained(self):
        rec = FlightRecorder(capacity=8, sla_ms=10_000.0,
                             emit_metrics=False)
        _finish_timeline(rec, "boom", duration=0.001, fail=True)
        assert [t.request_id for t in rec.slow()] == ["boom"]

    def test_cancelled_requests_finalize_but_are_not_retained(self):
        """A client disconnect is terminal but not a failure — a burst
        of ordinary disconnects must not evict real failures from the
        retention buffer."""
        rec = FlightRecorder(capacity=8, sla_ms=10_000.0,
                             emit_metrics=False)
        rec.record("gone", "enqueued")
        rec.record("gone", "cancelled")
        assert rec.get("gone").finalized
        assert rec.slow() == []

    def test_recent_zero_limit_returns_nothing(self):
        rec = FlightRecorder(capacity=8, emit_metrics=False)
        rec.record("r", "enqueued")
        assert rec.recent(0) == []
        assert rec.recent(-3) == []
        assert len(rec.recent(5)) == 1

    def test_slow_buffer_is_bounded(self):
        rec = FlightRecorder(capacity=64, slow_capacity=3, sla_ms=1.0,
                             emit_metrics=False)
        for i in range(8):
            _finish_timeline(rec, f"s{i}", duration=0.05)
        assert [t.request_id for t in rec.slow()] == ["s5", "s6", "s7"]

    def test_stage_latencies(self):
        rec = FlightRecorder(emit_metrics=False)
        t0 = 1000.0
        rec.record("r", "enqueued", ts=t0, priority="high")
        rec.record("r", "scheduled", ts=t0 + 0.5)
        rec.record("r", "dispatched", ts=t0 + 0.6, endpoint="ep0")
        rec.record("r", "admitted", ts=t0 + 0.7)
        rec.record("r", "prefill_start", ts=t0 + 0.75)
        rec.record("r", "first_token", ts=t0 + 1.0)
        rec.record("r", "completed", ts=t0 + 2.0, completion_tokens=11)
        lat = rec.get("r").stage_latencies()
        assert lat["queue_wait"] == pytest.approx(0.5)
        assert lat["dispatch"] == pytest.approx(0.1)
        assert lat["admission"] == pytest.approx(0.1)
        assert lat["prefill"] == pytest.approx(0.25)
        assert lat["ttft"] == pytest.approx(1.0)
        assert lat["decode_interarrival"] == pytest.approx(1.0 / 10)
        d = rec.get("r").to_dict()
        assert d["priority"] == "high" and d["endpoint"] == "ep0"

    def test_merge_stitches_and_dedups(self):
        rec = FlightRecorder(emit_metrics=False)
        rec.record("r", "enqueued", ts=1.0)
        remote = [{"stage": "admitted", "ts": 2.0, "host": "replica:1"},
                  {"stage": "completed", "ts": 3.0, "host": "replica:1"}]
        rec.merge("r", remote)
        rec.merge("r", remote)            # idempotent
        tl = rec.get("r")
        assert [e.stage for e in tl.sorted_events()] == [
            "enqueued", "admitted", "completed"]
        assert "replica:1" in tl.to_dict()["hosts"]
        # Merged terminal events do NOT finalize (the remote host owns
        # its own histograms); the local terminal stamp does.
        assert not tl.finalized

    def test_disabled_recorder_records_nothing(self):
        rec = FlightRecorder(enabled=False, emit_metrics=False)
        rec.record("r", "enqueued")
        assert len(rec) == 0 and rec.get("r") is None

    def test_reconfigure_in_place(self):
        rec = FlightRecorder(capacity=100, emit_metrics=False)
        for i in range(50):
            rec.record(f"r{i}", "enqueued")
        rec.reconfigure(capacity=10, sla_ms=1.0, enabled=True)
        assert len(rec) == 10
        cfg = ObservabilityConfig(enabled=True, recorder_capacity=7,
                                  sla_ms=123.0)
        singleton = observability.configure(cfg)
        assert singleton is observability.get_recorder()
        assert singleton.capacity == 7 and singleton.sla_ms == 123.0

    def test_concurrent_record_and_read(self):
        rec = FlightRecorder(capacity=128, sla_ms=1.0,
                             emit_metrics=False)
        stop = threading.Event()
        errors = []

        def writer(i):
            n = 0
            while not stop.is_set():
                _finish_timeline(rec, f"w{i}-{n}", duration=0.01)
                n += 1

        def reader():
            while not stop.is_set():
                try:
                    rec.recent(10)
                    rec.slow()
                    rec.get_stats()
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        threads = ([threading.Thread(target=writer, args=(i,))
                    for i in range(4)]
                   + [threading.Thread(target=reader) for _ in range(2)])
        for t in threads:
            t.start()
        time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        assert not errors
        assert len(rec) <= 128


# -- metrics ------------------------------------------------------------------

class TestStageMetrics:
    def test_terminal_event_feeds_stage_histograms(self):
        from llmq_tpu.metrics.registry import exposition
        rec = FlightRecorder(emit_metrics=True, sla_ms=1.0)
        t0 = time.time()
        rec.record("m", "enqueued", ts=t0, priority="realtime")
        rec.record("m", "scheduled", ts=t0 + 0.01)
        rec.record("m", "dispatched", ts=t0 + 0.02, endpoint="epX")
        rec.record("m", "admitted", ts=t0 + 0.03)
        rec.record("m", "prefill_start", ts=t0 + 0.03)
        rec.record("m", "first_token", ts=t0 + 0.05)
        rec.record("m", "completed", ts=t0 + 0.1, completion_tokens=4)
        # Observation is deferred off the hot path; the singleton is
        # flushed by exposition() itself, a standalone recorder here.
        assert rec.flush_metrics() == 1
        exp = exposition().decode()
        for family in ("llm_queue_stage_queue_wait_seconds",
                       "llm_queue_stage_dispatch_seconds",
                       "llm_queue_stage_admission_seconds",
                       "llm_queue_stage_prefill_seconds",
                       "llm_queue_ttft_seconds",
                       "llm_queue_decode_interarrival_seconds",
                       "llm_queue_sla_breaches_total",
                       "llm_queue_flightrecorder_timelines",
                       "llm_queue_dead_letter_depth"):
            assert family in exp, family
        assert ('llm_queue_ttft_seconds_count'
                '{endpoint="epX",priority="realtime"}') in exp
        # 100ms end-to-end breached the 1ms SLA.
        assert 'llm_queue_sla_breaches_total{priority="realtime"}' in exp


# -- chrome export ------------------------------------------------------------

class TestChromeExport:
    def test_hosts_become_processes_and_stages_slices(self):
        rec = FlightRecorder(emit_metrics=False)
        rec.record("r", "enqueued", ts=10.0, host="gw:1")
        rec.record("r", "dispatched", ts=10.1, host="gw:1")
        rec.merge("r", [{"stage": "admitted", "ts": 10.2,
                         "host": "replica:2"},
                        {"stage": "completed", "ts": 10.5,
                         "host": "replica:2"}])
        doc = chrome_trace([rec.get("r")])
        names = {e["args"].get("name") for e in doc["traceEvents"]
                 if e["ph"] == "M"}
        assert {"gw:1", "replica:2"} <= names
        slices = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert any(e["name"] == "enqueued→dispatched" for e in slices)
        assert any(e["name"] == "admitted→completed" for e in slices)

    def test_span_recorder_spans_stitch_in(self):
        from llmq_tpu.utils.profiling import SpanRecorder
        prof = SpanRecorder()
        with prof.span("engine.decode_chunk", active=3):
            pass
        rec = FlightRecorder(emit_metrics=False)
        rec.record("r", "enqueued")
        doc = chrome_trace([rec.get("r")], spans=prof.snapshot(),
                           jax_trace_dir="/tmp/xprof")
        assert any(e.get("name") == "engine.decode_chunk"
                   for e in doc["traceEvents"])
        assert doc["otherData"]["jax_trace_dir"] == "/tmp/xprof"


# -- REST routes --------------------------------------------------------------

def _echo_engine(name="obs0"):
    eng = InferenceEngine(EchoExecutor(batch_size=4), ByteTokenizer(),
                          name=name, enable_metrics=False)
    eng.start()
    return eng


class TestTraceRoutes:
    def test_trace_route_404_then_200(self):
        api = ApiServer(default_config())
        status, out, _ = api.dispatch(
            "GET", "/api/v1/requests/nope/trace", b"")
        assert status == 404
        observability.record("known-req", "enqueued", priority="low")
        status, out, _ = api.dispatch(
            "GET", "/api/v1/requests/known-req/trace", b"")
        assert status == 200
        assert out["request_id"] == "known-req"
        assert out["trace_id"] == trace_id_for("known-req")
        assert out["events"][0]["stage"] == "enqueued"

    def test_chrome_format(self):
        api = ApiServer(default_config())
        observability.record("chrome-req", "enqueued")
        observability.record("chrome-req", "completed")
        status, out, _ = api.dispatch(
            "GET", "/api/v1/requests/chrome-req/trace?format=chrome", b"")
        assert status == 200 and "traceEvents" in out

    def test_flightrecorder_admin_route(self):
        api = ApiServer(default_config())
        observability.record("fr-req", "enqueued")
        status, out, _ = api.dispatch(
            "GET", "/api/v1/admin/flightrecorder?limit=5", b"")
        assert status == 200
        assert out["enabled"] is True
        assert any(t["request_id"] == "fr-req" for t in out["recent"])

    def test_generate_sync_records_traceparent_and_returns_trace(self):
        eng = _echo_engine("obs-replica")
        api = ApiServer(default_config(), engine=eng)
        try:
            msg_id = "8c94e42e-6f3f-4a73-a18f-00000000aaaa"
            hdr = make_traceparent(msg_id)
            body = json.dumps({"id": msg_id, "content": "hello trace",
                               "user_id": "t", "timeout": 30}).encode()
            status, out, _ = api.dispatch(
                "POST", "/api/v1/generate", body,
                headers={"Traceparent": hdr})
            assert status == 200 and out["response"] == "hello trace"
            # The replica ships its stage events back for stitching...
            stages = [e["stage"] for e in out["trace"]]
            assert "dispatched" in stages and "completed" in stages
            assert "admitted" in stages and "first_token" in stages
            # ...and bound the caller's W3C context to its timeline.
            tl = observability.get_recorder().get(msg_id)
            dispatched = next(e for e in tl.events
                              if e.stage == "dispatched")
            assert dispatched.meta["traceparent"] == hdr
            assert tl.trace_id == parse_traceparent(hdr).trace_id
        finally:
            eng.stop()

    def test_sse_stream_carries_traceparent_header(self):
        import urllib.request
        eng = _echo_engine("obs-sse")
        api = ApiServer(default_config(), engine=eng)
        port = api.start(host="127.0.0.1", port=0)
        try:
            body = json.dumps({"content": "stream me", "user_id": "t",
                               "stream": True}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/messages", data=body,
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=15) as resp:
                tp = resp.headers.get("traceparent")
                rid = resp.headers.get("X-Request-Id")
                resp.read()
            assert parse_traceparent(tp) is not None
            assert parse_traceparent(tp).trace_id == trace_id_for(rid)
            tl = observability.get_recorder().get(rid)
            stages = {e.stage for e in tl.events}
            assert {"enqueued", "dispatched", "first_token",
                    "completed"} <= stages
        finally:
            api.stop()
            eng.stop()


# -- structured logging -------------------------------------------------------

class TestLogContext:
    def _record(self):
        return logging.LogRecord("llmq.test", logging.INFO, __file__, 1,
                                 "hello %s", ("world",), None)

    def test_json_formatter_merges_bound_fields(self):
        token = bind_log_context(request_id="r-1",
                                 conversation_id="c-1", endpoint="ep9")
        try:
            out = json.loads(JsonFormatter().format(self._record()))
        finally:
            reset_log_context(token)
        assert out["msg"] == "hello world"
        assert out["request_id"] == "r-1"
        assert out["conversation_id"] == "c-1"
        assert out["endpoint"] == "ep9"
        # Binding is scoped: after reset the fields are gone.
        out2 = json.loads(JsonFormatter().format(self._record()))
        assert "request_id" not in out2

    def test_console_formatter_appends_fields(self):
        token = bind_log_context(request_id="r-2")
        try:
            line = ConsoleFormatter().format(self._record())
        finally:
            reset_log_context(token)
        assert "request_id=r-2" in line

    def test_bindings_do_not_leak_across_threads(self):
        seen = {}

        def other():
            seen["ctx"] = json.loads(
                JsonFormatter().format(self._record()))

        token = bind_log_context(request_id="main-thread")
        try:
            t = threading.Thread(target=other)
            t.start()
            t.join()
        finally:
            reset_log_context(token)
        assert "request_id" not in seen["ctx"]

    def test_worker_thread_context_resets_after_each_dispatch(self):
        """Audit pin (ISSUE 6 satellite): ``Worker._run_one`` must
        leave its thread's log context EXACTLY as it found it after
        every dispatch — success or failure. Worker pool threads are
        reused across requests, so a leaked binding would stamp request
        B's log lines with request A's identity. The audit found the
        bind/reset pair correct (reset in ``finally``); this test pins
        it against regression."""
        from llmq_tpu.queueing.queue_manager import QueueManager
        from llmq_tpu.queueing.worker import Worker
        from llmq_tpu.utils.logging import current_log_context

        seen = []

        def fn(ctx, msg):
            seen.append(current_log_context())
            if msg.content == "boom":
                raise RuntimeError("boom")

        cfg = default_config()
        cfg.queue.enable_metrics = False
        mgr = QueueManager("ctx-audit", config=cfg)
        worker = Worker("ctx-audit", mgr, fn)
        try:
            reset_log_context()   # known-clean baseline on this thread

            mgr.push_message(Message(id="ctx-a", content="ok",
                                     conversation_id="conv-a",
                                     timeout=5.0))
            worker.process_one_sync(mgr.pop_message("normal"))
            # Bound during the dispatch, gone after it.
            assert seen[0].get("request_id") == "ctx-a"
            assert seen[0].get("conversation_id") == "conv-a"
            assert current_log_context() == {}

            # Failure path: the reset runs in a finally, so a raising
            # process_fn must not leak either.
            mgr.push_message(Message(id="ctx-b", content="boom",
                                     timeout=5.0))
            worker.process_one_sync(mgr.pop_message("normal"))
            assert seen[1].get("request_id") == "ctx-b"
            # No bleed of the PREVIOUS request's fields into this one.
            assert seen[1].get("conversation_id") != "conv-a"
            assert current_log_context() == {}

            # Nested on top of an outer binding: the token restore must
            # bring back exactly the outer context, not empty it.
            outer = bind_log_context(service="gateway")
            try:
                mgr.push_message(Message(id="ctx-c", content="ok",
                                         timeout=5.0))
                worker.process_one_sync(mgr.pop_message("normal"))
                assert seen[2].get("request_id") == "ctx-c"
                assert seen[2].get("service") == "gateway"  # merged
                assert current_log_context() == {"service": "gateway"}
            finally:
                reset_log_context(outer)
        finally:
            worker.stop()

    def test_worker_binds_request_context(self):
        from llmq_tpu.core.types import Priority
        from llmq_tpu.queueing.queue_manager import QueueManager
        from llmq_tpu.queueing.worker import Worker
        cfg = default_config()
        cfg.queue.enable_metrics = False
        mgr = QueueManager("obs-ctx", config=cfg)
        captured = {}

        def process(ctx, msg):
            from llmq_tpu.utils.logging import current_log_context
            captured.update(current_log_context())

        w = Worker("ctx-test", mgr, process)
        msg = Message(id="bound-1", content="x",
                      conversation_id="conv-9",
                      priority=Priority.NORMAL)
        mgr.push_message(msg)
        w.process_batch()
        assert captured["request_id"] == "bound-1"
        assert captured["conversation_id"] == "conv-9"


# -- lifecycle integration (engine) -------------------------------------------

class TestEngineTimeline:
    def test_engine_stamps_lifecycle_stages(self):
        eng = _echo_engine("obs-engine")
        try:
            msg = Message(id="eng-trace-1", content="time me",
                          timeout=30.0)
            observability.record(msg.id, "enqueued", priority="normal")
            eng.process_fn(None, msg)
            tl = observability.get_recorder().get(msg.id)
            stages = [e.stage for e in tl.sorted_events()]
            for s in ("enqueued", "admitted", "prefill_start",
                      "first_token", "completed"):
                assert s in stages, (s, stages)
            # Wall-clock ordering survived the perf_counter conversion.
            idx = {s: stages.index(s) for s in stages}
            assert idx["admitted"] <= idx["first_token"] < idx["completed"]
            lat = tl.stage_latencies()
            assert "ttft" in lat and lat["ttft"] >= 0
        finally:
            eng.stop()


# -- overhead guard (acceptance criterion: <= 3 % on the echo path) -----------

class TestOverheadGuard:
    def test_per_request_stamping_under_3pct_of_echo_request(self):
        """The full per-request trace cost (the exact 9-event stamping
        pattern the serve path produces, including terminal finalize)
        must stay under 3 % of one request through the echo-engine
        bench path (queue → worker → engine, bench_poisson_echo's
        wiring) — the bound the acceptance criterion puts on
        trace-plane overhead. Deterministic decomposition rather than
        a wall-clock A/B: run-to-run scheduler noise on shared CI
        exceeds 3 %, the per-call stamping cost does not."""
        from llmq_tpu.queueing.queue_manager import QueueManager
        from llmq_tpu.queueing.worker import Worker
        eng = _echo_engine("obs-bench")
        cfg = default_config()
        cfg.queue.enable_metrics = False
        cfg.queue.worker.process_interval = 0.002
        cfg.queue.worker.max_batch_size = 128
        mgr = QueueManager("obs-bench", config=cfg)
        worker = Worker("obs-bench", mgr, eng.process_fn)
        worker.start()
        try:
            n = 40
            t0 = time.perf_counter()
            for i in range(n):
                mgr.push_message(Message(id=f"bench-{i}",
                                         content="measure me",
                                         timeout=30.0))
            deadline = time.time() + 30
            while time.time() < deadline:
                if (worker.stats.to_dict()["succeeded"] >= n):
                    break
                time.sleep(0.002)
            per_request = (time.perf_counter() - t0) / n
            assert worker.stats.to_dict()["succeeded"] >= n
        finally:
            worker.stop()
            eng.stop()

        import gc
        rec = FlightRecorder(capacity=8192, sla_ms=5000.0,
                             emit_metrics=True)

        def stamp_batch(k0: int, m: int) -> float:
            t0 = time.perf_counter()
            for i in range(k0, k0 + m):
                rid = f"ovh-{i}"
                ts = time.time()
                rec.record(rid, "enqueued", ts=ts, priority="normal")
                rec.record(rid, "scheduled", ts=ts, worker="w0",
                           priority="normal", retry_count=0)
                rec.record(rid, "dispatched", ts=ts, endpoint="e0",
                           reason="select", priority="normal")
                rec.record_many(rid, [
                    ("admitted", ts,
                     {"engine": "e0", "priority": "normal"}),
                    ("prefill_start", ts, {"engine": "e0"}),
                    ("prefill_done", ts, {"engine": "e0"}),
                    ("first_token", ts, {"engine": "e0"}),
                    ("completed", ts, {"engine": "e0",
                                       "completion_tokens": 16}),
                ])
                rec.record(rid, "completed", ts=ts, worker="w0",
                           priority="normal", endpoint="e0")
            return (time.perf_counter() - t0) / m
        # Best-of-batches: the stamping cost is deterministic; GC
        # pauses and neighbor-test threads are not. The minimum is the
        # honest per-request cost.
        gc.collect()
        per_timeline = min(stamp_batch(k * 100, 100) for k in range(6))
        assert per_timeline < 0.03 * per_request, (
            f"trace stamping {per_timeline * 1e6:.1f}µs/request vs "
            f"echo bench request {per_request * 1e6:.1f}µs — over the "
            f"3% budget")
