"""Pallas paged-attention kernel vs the pure-JAX semantics reference.

The kernel (ops/pallas/paged_attention.py) runs in interpret mode here —
CPU CI covers the kernel body (DMA schedule, online softmax, masking)
without TPU hardware; on-device numerics are exercised by bench.py on
the real chip.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from llmq_tpu.ops.attention import (  # noqa: E402
    blockwise_prefill_attention,
    causal_prefill_attention,
    paged_decode_attention,
)
from llmq_tpu.ops.pallas.paged_attention import (  # noqa: E402
    paged_decode_attention_pallas)


def _paged_setup(rng, *, B=4, H=8, Hkv=2, D=64, ps=16, P=32, mp=6,
                 dtype=jnp.float32):
    q = jnp.asarray(rng.standard_normal((B, H, D)), dtype)
    k = jnp.asarray(rng.standard_normal((P, ps, Hkv, D)), dtype)
    v = jnp.asarray(rng.standard_normal((P, ps, Hkv, D)), dtype)
    # Distinct non-zero pages per sequence (page 0 reserved).
    ids = rng.permutation(np.arange(1, P))[: B * mp].reshape(B, mp)
    bt = jnp.asarray(ids, jnp.int32)
    return q, k, v, bt


def _flat(pool):
    """Kernel-layout view: (P, ps, H_kv, D) → flat (P, ps, H_kv·D)."""
    return pool.reshape(pool.shape[0], pool.shape[1], -1)


def _flat2(pool):
    """Stacked-pool view: (L, P, ps, H_kv, D) → (L, P, ps, H_kv·D)."""
    return pool.reshape(*pool.shape[:3], -1)


class TestPagedDecodeKernel:
    def test_matches_reference(self):
        rng = np.random.default_rng(0)
        q, k, v, bt = _paged_setup(rng)
        # Lengths hit: single token, mid-page, page boundary, full window.
        sl = jnp.asarray([1, 17, 32, 96], jnp.int32)
        ref = paged_decode_attention(q, k, v, bt, sl)
        out = paged_decode_attention_pallas(q, _flat(k), _flat(v), bt, sl,
                                            pages_per_chunk=2,
                                            interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-2, rtol=3e-2)

    def test_bf16_cache(self):
        rng = np.random.default_rng(1)
        q, k, v, bt = _paged_setup(rng, dtype=jnp.bfloat16)
        sl = jnp.asarray([5, 40, 96, 64], jnp.int32)
        ref = paged_decode_attention(q, k, v, bt, sl).astype(jnp.float32)
        out = paged_decode_attention_pallas(q, _flat(k), _flat(v), bt, sl, pages_per_chunk=4,
            interpret=True).astype(jnp.float32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=5e-2, rtol=5e-2)

    def test_chunk_width_irrelevant(self):
        rng = np.random.default_rng(2)
        q, k, v, bt = _paged_setup(rng)
        sl = jnp.asarray([9, 25, 50, 80], jnp.int32)
        a = paged_decode_attention_pallas(q, _flat(k), _flat(v), bt, sl,
                                          pages_per_chunk=1, interpret=True)
        b = paged_decode_attention_pallas(q, _flat(k), _flat(v), bt, sl,
                                          pages_per_chunk=3, interpret=True)
        c = paged_decode_attention_pallas(q, _flat(k), _flat(v), bt, sl,
                                          pages_per_chunk=6, interpret=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-2, rtol=2e-2)
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   atol=2e-2, rtol=2e-2)

    def test_dead_pages_never_read(self):
        """Garbage (NaN) in pages beyond seq_len must not leak: dead
        pages are skipped by the DMA schedule and masked in compute."""
        rng = np.random.default_rng(3)
        q, k, v, bt = _paged_setup(rng)
        sl = jnp.asarray([1, 16, 33, 90], jnp.int32)
        k_np, v_np = np.asarray(k).copy(), np.asarray(v).copy()
        ps = k_np.shape[1]
        mp = bt.shape[1]
        for b in range(bt.shape[0]):
            n_live = -(-int(sl[b]) // ps)
            for dead in np.asarray(bt)[b, n_live:mp]:
                k_np[dead] = np.nan
                v_np[dead] = np.nan
        out = paged_decode_attention_pallas(
            jnp.asarray(q), _flat(jnp.asarray(k_np)),
            _flat(jnp.asarray(v_np)), bt, sl,
            pages_per_chunk=2, interpret=True)
        assert np.isfinite(np.asarray(out)).all()

    def test_model_dispatch_under_interpret(self, monkeypatch):
        """forward_decode routes through the kernel when
        LLMQ_PALLAS=interpret and produces the same logits as pure JAX."""
        monkeypatch.setenv("LLMQ_PALLAS", "0")
        from llmq_tpu.models.llama import (forward_decode, get_config,
                                           init_kv_pages, init_params)
        # H_kv·head_dim must be 128-aligned for the kernel path: 2·64.
        cfg = get_config("llama3-tiny", max_seq_len=64, dim=256,
                         n_heads=4, n_kv_heads=2)
        params = init_params(jax.random.PRNGKey(0), cfg)
        cache = init_kv_pages(cfg, 16, 8)
        bt = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
        toks = jnp.asarray([7], jnp.int32)
        pos = jnp.asarray([3], jnp.int32)
        ref, _ = forward_decode(params, cfg, toks, pos, cache, bt)
        monkeypatch.setenv("LLMQ_PALLAS", "interpret")
        # The env var is read at trace time; equal configs share a jit
        # cache entry, so force a retrace to route through the kernel.
        jax.clear_caches()
        out, _ = forward_decode(params, cfg, toks, pos, cache, bt)
        # bf16 compute: kernel and pure-JAX paths accumulate in
        # different orders, so logits at ~2.5 magnitude legitimately
        # differ by a few bf16 ulps (~0.016 each) — 5e-2 covers that
        # without masking a real indexing/masking bug (those show up
        # as O(1) divergence on many elements, not 0.03 on one).
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=5e-2, rtol=5e-2)
        jax.clear_caches()  # don't leak interpret-mode traces to others


class TestKvWriteKernels:
    def test_decode_row_write(self):
        from llmq_tpu.ops.pallas.kv_write import kv_cache_write_pallas
        rng = np.random.default_rng(0)
        L, P, ps, Hkv, D, N = 3, 40, 8, 2, 64, 12
        k = jnp.asarray(rng.standard_normal((L, P, ps, Hkv, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((L, P, ps, Hkv, D)), jnp.float32)
        kn = jnp.asarray(rng.standard_normal((N, Hkv, D)), jnp.float32)
        vn = jnp.asarray(rng.standard_normal((N, Hkv, D)), jnp.float32)
        page = jnp.asarray(np.arange(1, N + 1), jnp.int32)   # distinct
        slot = jnp.asarray(np.arange(N) % ps, jnp.int32)
        kf, vf = _flat2(k), _flat2(v)
        ref_k = kf.at[1, page, slot].set(kn.reshape(N, -1))
        ref_v = vf.at[1, page, slot].set(vn.reshape(N, -1))
        ok, ov = kv_cache_write_pallas(kf, vf, kn.reshape(N, -1),
                                       vn.reshape(N, -1), page, slot, 1,
                                       interpret=True)
        np.testing.assert_array_equal(np.asarray(ok), np.asarray(ref_k))
        np.testing.assert_array_equal(np.asarray(ov), np.asarray(ref_v))

    @pytest.mark.parametrize("start,n_tok", [(0, 32), (5, 20), (13, 32),
                                             (8, 8), (19, 1)])
    def test_prefill_page_write(self, start, n_tok):
        """Page-RMW prefill write == scatter, incl. partial edge pages
        and preservation of pre-existing KV before the chunk start."""
        from llmq_tpu.ops.pallas.kv_write import kv_prefill_write_pallas
        rng = np.random.default_rng(start * 100 + n_tok)
        L, P, ps, Hkv, D = 2, 16, 8, 2, 64
        mp = 8                                    # block-table width
        k = jnp.asarray(rng.standard_normal((L, P, ps, Hkv, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((L, P, ps, Hkv, D)), jnp.float32)
        bt = jnp.asarray(rng.permutation(np.arange(1, P))[:mp], jnp.int32)
        kn = jnp.asarray(rng.standard_normal((n_tok, Hkv, D)), jnp.float32)
        vn = jnp.asarray(rng.standard_normal((n_tok, Hkv, D)), jnp.float32)
        # scatter reference
        pos = start + np.arange(n_tok)
        page = np.asarray(bt)[pos // ps]
        slot = pos % ps
        kf, vf = _flat2(k), _flat2(v)
        ref_k = kf.at[1, page, slot].set(kn.reshape(n_tok, -1))
        ref_v = vf.at[1, page, slot].set(vn.reshape(n_tok, -1))
        # kernel: page-aligned buffer, bucket length T >= n_tok
        T = 32
        n_wp = T // ps + 1
        ak = np.zeros((n_wp * ps, Hkv * D), np.float32)
        av = np.zeros((n_wp * ps, Hkv * D), np.float32)
        off = start % ps
        ak[off:off + n_tok] = np.asarray(kn).reshape(n_tok, -1)
        av[off:off + n_tok] = np.asarray(vn).reshape(n_tok, -1)
        ok, ov = kv_prefill_write_pallas(
            kf, vf, jnp.asarray(ak), jnp.asarray(av), bt,
            jnp.int32(start), jnp.int32(n_tok), 1, interpret=True)
        np.testing.assert_array_equal(np.asarray(ok), np.asarray(ref_k))
        np.testing.assert_array_equal(np.asarray(ov), np.asarray(ref_v))

    def test_prefill_write_nonmultiple_bucket(self, monkeypatch):
        """Bucket T not a multiple of page_size with a mid-page
        continuation start: the aligned buffer must not clamp (review
        regression: T//ps+1 pages under-allocated → silent KV shift)."""
        from llmq_tpu.ops.attention import paged_kv_write_prefill
        rng = np.random.default_rng(7)
        L, P, ps, Hkv, D = 2, 16, 16, 2, 64
        T, start, n_tok = 24, 28, 24         # off=12, off+T=36 > 2*ps
        mp = 8
        k_pool = jnp.asarray(rng.standard_normal((L, P, ps, Hkv * D)),
                             jnp.float32)
        v_pool = jnp.asarray(rng.standard_normal((L, P, ps, Hkv * D)),
                             jnp.float32)
        bt = jnp.asarray(np.arange(1, mp + 1), jnp.int32)[None]
        k = jnp.asarray(rng.standard_normal((1, T, Hkv, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((1, T, Hkv, D)), jnp.float32)
        positions = (start + jnp.arange(T))[None].astype(jnp.int32)
        lengths = jnp.asarray([n_tok], jnp.int32)
        monkeypatch.setenv("LLMQ_PALLAS", "0")
        jax.clear_caches()
        rk, rv = paged_kv_write_prefill(k_pool, v_pool, k, v, bt,
                                        positions, lengths, 1)
        monkeypatch.setenv("LLMQ_PALLAS", "interpret")
        jax.clear_caches()
        ok, ov = paged_kv_write_prefill(k_pool, v_pool, k, v, bt,
                                        positions, lengths, 1)
        jax.clear_caches()
        np.testing.assert_array_equal(np.asarray(ok), np.asarray(rk))
        np.testing.assert_array_equal(np.asarray(ov), np.asarray(rv))

    def test_forward_prefill_dispatch_interpret(self, monkeypatch):
        """forward_prefill B=1 routes through the prefill-write kernel
        under LLMQ_PALLAS=interpret and matches the scatter path."""
        from llmq_tpu.models.llama import (forward_prefill, get_config,
                                           init_kv_pages, init_params)
        cfg = get_config("llama3-tiny", max_seq_len=64, dim=256,
                         n_heads=4, n_kv_heads=2)
        params = init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray([[5, 9, 2, 7, 1, 3, 8, 4]], jnp.int32)
        pos = jnp.arange(8)[None, :].astype(jnp.int32)
        lens = jnp.asarray([8], jnp.int32)
        bt = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
        monkeypatch.setenv("LLMQ_PALLAS", "0")
        jax.clear_caches()
        cache = init_kv_pages(cfg, 16, 8)
        ref_logits, ref_cache = forward_prefill(params, cfg, toks, pos,
                                                lens, cache, bt)
        monkeypatch.setenv("LLMQ_PALLAS", "interpret")
        jax.clear_caches()
        cache = init_kv_pages(cfg, 16, 8)
        out_logits, out_cache = forward_prefill(params, cfg, toks, pos,
                                                lens, cache, bt)
        jax.clear_caches()
        np.testing.assert_allclose(np.asarray(out_logits),
                                   np.asarray(ref_logits),
                                   atol=3e-2, rtol=3e-2)
        # written pages identical (pages 1..4 hold the 8 tokens)
        np.testing.assert_allclose(
            np.asarray(out_cache["k"][:, 1:5]),
            np.asarray(ref_cache["k"][:, 1:5]), atol=3e-2, rtol=3e-2)

    def test_batched_prefill_kernel_route_interpret(self, monkeypatch):
        """B>1 forward_prefill with the serving executor's
        pallas_batched_prefill opt-in routes the row-looped kernels
        (interpret mode) and matches the pure-JAX path — the production
        batched-admission route (r4), otherwise only exercised on TPU."""
        import dataclasses

        from llmq_tpu.models.llama import (forward_prefill, get_config,
                                           init_kv_pages, init_params)
        cfg = get_config("llama3-tiny", max_seq_len=64, dim=256,
                         n_heads=4, n_kv_heads=2)
        params = init_params(jax.random.PRNGKey(0), cfg)
        B, T = 3, 8
        rng = np.random.default_rng(0)
        toks = jnp.asarray(rng.integers(1, 500, (B, T)), jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(T), (B, T)).astype(jnp.int32)
        lens = jnp.asarray([8, 5, 8], jnp.int32)
        bt = jnp.asarray(np.arange(1, B * 4 + 1, dtype=np.int32)
                         .reshape(B, 4))
        monkeypatch.setenv("LLMQ_PALLAS", "0")
        jax.clear_caches()
        cache = init_kv_pages(cfg, 16, 8)
        ref_logits, ref_cache = forward_prefill(params, cfg, toks, pos,
                                                lens, cache, bt)
        monkeypatch.setenv("LLMQ_PALLAS", "interpret")
        jax.clear_caches()
        kcfg = dataclasses.replace(cfg, pallas_batched_prefill=True)
        cache = init_kv_pages(cfg, 16, 8)
        out_logits, out_cache = forward_prefill(params, kcfg, toks, pos,
                                                lens, cache, bt)
        jax.clear_caches()
        # Compare only VALID rows' logits (padding rows differ — the
        # kernel derives q positions from positions[b, 0] and discards
        # nothing; the executor slices at lengths-1).
        for b in range(B):
            n = int(lens[b])
            np.testing.assert_allclose(
                np.asarray(out_logits[b, :n]),
                np.asarray(ref_logits[b, :n]), atol=3e-2, rtol=3e-2)
        np.testing.assert_allclose(
            np.asarray(out_cache["k"][:, 1:13]),
            np.asarray(ref_cache["k"][:, 1:13]), atol=3e-2, rtol=3e-2)


class TestFusedDecode:
    def test_matches_unfused(self, monkeypatch):
        """Fused write+attention == scatter-write + pooled attention,
        including page-boundary positions and the pool update."""
        from llmq_tpu.ops.pallas.fused_decode import (
            fused_decode_attention_pallas)
        from llmq_tpu.ops.attention import (paged_decode_attention_pooled,
                                            paged_kv_write)
        monkeypatch.setenv("LLMQ_PALLAS", "0")   # pure reference path
        rng = np.random.default_rng(3)
        L, P, ps, Hkv, D, H, B = 2, 24, 8, 2, 64, 4, 3
        mp = 6
        k_pool = jnp.asarray(rng.standard_normal((L, P, ps, Hkv * D)),
                             jnp.float32)
        v_pool = jnp.asarray(rng.standard_normal((L, P, ps, Hkv * D)),
                             jnp.float32)
        bt = jnp.asarray(
            rng.permutation(np.arange(1, P))[:B * mp].reshape(B, mp),
            jnp.int32)
        q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
        kn = jnp.asarray(rng.standard_normal((B, Hkv, D)), jnp.float32)
        vn = jnp.asarray(rng.standard_normal((B, Hkv, D)), jnp.float32)
        positions = jnp.asarray([0, 15, 37], jnp.int32)  # page edges
        seq_lens = positions + 1
        page_of = bt[jnp.arange(B), positions // ps]
        slot_of = positions % ps
        rk, rv = paged_kv_write(k_pool, v_pool, kn, vn, page_of,
                                slot_of, 1)
        ref = paged_decode_attention_pooled(q, rk, rv, bt, seq_lens, 1)
        attn, (ok, ov) = fused_decode_attention_pallas(
            q, kn, vn, k_pool, v_pool, bt, seq_lens, page_of, 1,
            pages_per_chunk=2, interpret=True)
        np.testing.assert_allclose(np.asarray(attn), np.asarray(ref),
                                   atol=3e-2, rtol=3e-2)
        np.testing.assert_array_equal(np.asarray(ok), np.asarray(rk))
        np.testing.assert_array_equal(np.asarray(ov), np.asarray(rv))

    def test_full_row_tile_mixed_lengths(self, monkeypatch):
        """B=8 exercises the real R=8 tile path (cross-pair prefetch
        chain, SMEM slot parity, per-row merge in a shared tile) with
        wildly mixed seq_lens including zero — B=3 degenerates to R=1
        and would leave all of that untested."""
        from llmq_tpu.ops.pallas.fused_decode import (
            fused_decode_attention_pallas)
        from llmq_tpu.ops.attention import (paged_decode_attention_pooled,
                                            paged_kv_write)
        monkeypatch.setenv("LLMQ_PALLAS", "0")   # pure reference path
        rng = np.random.default_rng(11)
        L, P, ps, Hkv, D, H, B = 2, 80, 8, 2, 64, 4, 8
        mp = 8
        k_pool = jnp.asarray(rng.standard_normal((L, P, ps, Hkv * D)),
                             jnp.float32)
        v_pool = jnp.asarray(rng.standard_normal((L, P, ps, Hkv * D)),
                             jnp.float32)
        bt = jnp.asarray(
            rng.permutation(np.arange(1, P))[:B * mp].reshape(B, mp),
            jnp.int32)
        q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
        kn = jnp.asarray(rng.standard_normal((B, Hkv, D)), jnp.float32)
        vn = jnp.asarray(rng.standard_normal((B, Hkv, D)), jnp.float32)
        # page edges, full window, and a zero-length (inactive) row
        seq_lens = jnp.asarray([1, 8, 9, 0, 64, 33, 16, 57], jnp.int32)
        positions = jnp.maximum(seq_lens - 1, 0)
        live = seq_lens > 0
        page_of = jnp.where(live, bt[jnp.arange(B), positions // ps], 0)
        slot_of = positions % ps
        kn_w = jnp.where(live[:, None, None], kn, 0)
        vn_w = jnp.where(live[:, None, None], vn, 0)
        rk, rv = paged_kv_write(k_pool, v_pool, kn_w, vn_w, page_of,
                                slot_of, 1)
        ref = paged_decode_attention_pooled(q, rk, rv, bt, seq_lens, 1)
        attn, (ok, ov) = fused_decode_attention_pallas(
            q, kn, vn, k_pool, v_pool, bt, seq_lens, page_of, 1,
            pages_per_chunk=2, interpret=True)
        a, r = np.asarray(attn), np.asarray(ref)
        mask = np.asarray(live)
        np.testing.assert_allclose(a[mask], r[mask], atol=3e-2, rtol=3e-2)
        # zero-length row emits exactly 0 (the documented contract)
        assert np.all(a[~mask] == 0)
        # pools: live rows' pages updated; the seq-0 row wrote nothing
        # except possibly reserved page 0 (never read) — compare all
        # non-reserved pages.
        np.testing.assert_array_equal(np.asarray(ok)[:, 1:],
                                      np.asarray(rk)[:, 1:])
        np.testing.assert_array_equal(np.asarray(ov)[:, 1:],
                                      np.asarray(rv)[:, 1:])


class TestPrefillAttentionKernel:
    @pytest.mark.parametrize("start", [0, 24])
    def test_matches_blockwise(self, start):
        """Paged prefill attention kernel == gather + blockwise, for a
        fresh prompt (start=0) and a continuation chunk (start=24)."""
        from llmq_tpu.ops.pallas.prefill_attention import (
            paged_prefill_attention_pallas)
        rng = np.random.default_rng(start)
        L, P, ps, Hkv, D, H = 2, 24, 8, 2, 64, 4
        T, mp = 16, 8
        k_pool = jnp.asarray(rng.standard_normal((L, P, ps, Hkv * D)),
                             jnp.float32)
        v_pool = jnp.asarray(rng.standard_normal((L, P, ps, Hkv * D)),
                             jnp.float32)
        bt = jnp.asarray(rng.permutation(np.arange(1, P))[:mp], jnp.int32)
        q = jnp.asarray(rng.standard_normal((1, T, H, D)), jnp.float32)
        positions = (start + jnp.arange(T))[None, :].astype(jnp.int32)
        seq_lens = jnp.asarray([start + T], jnp.int32)

        k_hist = k_pool[1, bt[None]].reshape(1, mp * ps, Hkv, D)
        v_hist = v_pool[1, bt[None]].reshape(1, mp * ps, Hkv, D)
        # (gathered VALUES may be unflattened freely; the pool may not)
        ref = blockwise_prefill_attention(q, k_hist, v_hist, positions,
                                          seq_lens)
        out = paged_prefill_attention_pallas(
            q[0], k_pool, v_pool, bt, jnp.int32(start), 1,
            pages_per_chunk=2, q_block=8, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref[0]),
                                   atol=3e-2, rtol=3e-2)


class TestBlockwisePrefill:
    def test_matches_full_softmax(self):
        rng = np.random.default_rng(4)
        B, T, S, H, Hkv, D = 2, 8, 48, 8, 2, 32
        q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, S, Hkv, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, S, Hkv, D)), jnp.float32)
        positions = jnp.asarray(
            np.stack([np.arange(T), np.arange(10, 10 + T)]), jnp.int32)
        seq_lens = jnp.asarray([T, 10 + T], jnp.int32)

        # Full-softmax reference with the same mask.
        qg = q.reshape(B, T, Hkv, H // Hkv, D)
        logits = jnp.einsum("btgrd,bsgd->bgrts", qg, k) * (D ** -0.5)
        kv_pos = jnp.arange(S)[None, None, :]
        mask = ((kv_pos <= positions[:, :, None])
                & (kv_pos < seq_lens[:, None, None]))
        logits = jnp.where(mask[:, None, None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        ref = jnp.einsum("bgrts,bsgd->btgrd", probs, v).reshape(B, T, H, D)

        for bs in (8, 16, 48, 512):
            out = blockwise_prefill_attention(q, k, v, positions, seq_lens,
                                              block_size=bs)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=2e-2, rtol=2e-2)

    def test_matches_causal_prefill(self):
        """Zero-offset case must agree with causal_prefill_attention."""
        rng = np.random.default_rng(5)
        B, T, H, Hkv, D = 2, 16, 4, 2, 32
        q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, T, Hkv, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, T, Hkv, D)), jnp.float32)
        positions = jnp.broadcast_to(jnp.arange(T), (B, T)).astype(jnp.int32)
        seq_lens = jnp.full((B,), T, jnp.int32)
        ref = causal_prefill_attention(q, k, v)
        out = blockwise_prefill_attention(q, k, v, positions, seq_lens,
                                          block_size=4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-2, rtol=2e-2)


class TestFusedDecodeQ8:
    def _mk(self, rng, B=8, L=2, P=33, ps=8, Hkv=8, D=16, H=16, mp=4):
        from llmq_tpu.ops.quant import quantize_kv_rows
        GD = Hkv * D
        k_pool = jnp.zeros((L, P, ps, GD), jnp.int8)
        v_pool = jnp.zeros((L, P, ps, GD), jnp.int8)
        ks = jnp.zeros((L, P, Hkv, ps), jnp.bfloat16)
        vs = jnp.zeros((L, P, Hkv, ps), jnp.bfloat16)
        # Pre-populate history through the PURE write path so both
        # implementations read identical quantized pools.
        hist_k = jnp.asarray(rng.standard_normal((B, mp * ps, Hkv, D)),
                             jnp.float32)
        hist_v = jnp.asarray(rng.standard_normal((B, mp * ps, Hkv, D)),
                             jnp.float32)
        bt = jnp.asarray(
            rng.permutation(np.arange(1, P))[:B * mp].reshape(B, mp),
            jnp.int32)
        return (k_pool, v_pool, ks, vs), hist_k, hist_v, bt

    def test_matches_pure_q8(self, monkeypatch):
        from llmq_tpu.ops.attention import paged_decode_step_q8
        from llmq_tpu.ops.pallas.fused_decode import (
            fused_decode_attention_q8_pallas)
        from llmq_tpu.ops.quant import quantize_kv_rows

        rng = np.random.default_rng(7)
        B, Hkv, D, H, ps, mp = 8, 8, 16, 16, 8, 4
        pools, hist_k, hist_v, bt = self._mk(rng)
        # Write two history tokens per row via the pure path.
        monkeypatch.setenv("LLMQ_PALLAS", "0")
        positions = jnp.asarray([0, 3, 7, 8, 15, 20, 25, 29], jnp.int32)
        q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.bfloat16)
        for step in range(2):
            pos = positions + step
            page_of = bt[jnp.arange(B), pos // ps]
            slot_of = pos % ps
            _, pools = paged_decode_step_q8(
                q, hist_k[:, step], hist_v[:, step], pools, bt, pos + 1,
                page_of, slot_of, 1)
        # Step 3: pure vs kernel from the SAME pool state.
        pos = positions + 2
        seq_lens = pos + 1
        page_of = bt[jnp.arange(B), pos // ps]
        slot_of = pos % ps
        kn, vn = hist_k[:, 2], hist_v[:, 2]
        ref_attn, ref_pools = paged_decode_step_q8(
            q, kn, vn, pools, bt, seq_lens, page_of, slot_of, 1)
        kq, ksc = quantize_kv_rows(kn)
        vq, vsc = quantize_kv_rows(vn)
        attn, out_pools = fused_decode_attention_q8_pallas(
            q, kq, ksc, vq, vsc, pools, bt, seq_lens, page_of, 1,
            pages_per_chunk=2, interpret=True)
        np.testing.assert_allclose(
            np.asarray(attn, np.float32), np.asarray(ref_attn, np.float32),
            atol=3e-2, rtol=3e-2)
        for a, b in zip(out_pools, ref_pools):
            np.testing.assert_array_equal(
                np.asarray(a, np.float32), np.asarray(b, np.float32))
