"""Parallel-layer tests on the virtual 8-device CPU mesh: mesh building,
TP sharding correctness (sharded forward == single-device forward), DP
batch sharding, and the sharded train step used by dryrun_multichip."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from llmq_tpu.models.llama import (
    forward_decode,
    forward_prefill,
    init_kv_pages,
    init_params,
    llama3_tiny,
    loss_fn,
)
from llmq_tpu.parallel import (
    batch_sharding,
    kv_cache_shardings,
    make_mesh,
    param_shardings,
    shard_params,
    single_device_mesh,
)

# 8 heads / 8 kv heads so an 8-way tp axis divides evenly on the test mesh.
CFG = llama3_tiny(dtype=jnp.float32, n_heads=8, n_kv_heads=8, dim=64,
                  ffn_dim=128, vocab_size=256)
PAGE, NPAGES, MAXP = 4, 32, 4


class TestMesh:
    def test_make_mesh_shapes(self):
        mesh = make_mesh({"dp": 2, "tp": 4})
        assert mesh.shape == {"dp": 2, "tp": 4}

    def test_infer_axis(self):
        mesh = make_mesh({"dp": 2, "tp": -1})
        assert mesh.shape["tp"] == 4

    def test_bad_shape_raises(self):
        with pytest.raises(ValueError):
            make_mesh({"dp": 3, "tp": 3})

    def test_single_device_mesh(self):
        mesh = single_device_mesh()
        assert mesh.shape == {"dp": 1, "tp": 1}


class TestTPCorrectness:
    def test_sharded_prefill_matches_single(self):
        """The whole point of GSPMD: same numbers, more chips."""
        params = init_params(jax.random.PRNGKey(0), CFG)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                  CFG.vocab_size)
        pos = jnp.broadcast_to(jnp.arange(8), (2, 8))
        lens = jnp.array([8, 8])
        bt = jnp.array([[1, 2, 0, 0], [3, 4, 0, 0]], jnp.int32)
        cache = init_kv_pages(CFG, NPAGES, PAGE, jnp.float32)
        ref, _ = forward_prefill(params, CFG, toks, pos, lens, cache, bt)

        mesh = make_mesh({"tp": 8})
        sharded = shard_params(params, param_shardings(CFG, mesh))
        cache_sh = jax.device_put(
            init_kv_pages(CFG, NPAGES, PAGE, jnp.float32),
            kv_cache_shardings(CFG, mesh))
        got, new_cache = forward_prefill(sharded, CFG, toks, pos, lens,
                                         cache_sh, bt)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)
        # Param shardings really split the head dim across chips.
        wq = sharded["layers"]["wq"]
        assert wq.sharding.spec == P(None, None, "tp")

    def test_sharded_decode_matches_single(self):
        params = init_params(jax.random.PRNGKey(0), CFG)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0,
                                  CFG.vocab_size)
        pos = jnp.broadcast_to(jnp.arange(4), (2, 4))
        lens = jnp.array([4, 4])
        bt = jnp.array([[1, 0, 0, 0], [2, 0, 0, 0]], jnp.int32)
        cache = init_kv_pages(CFG, NPAGES, PAGE, jnp.float32)
        _, cache = forward_prefill(params, CFG, toks, pos, lens, cache, bt)
        ref, _ = forward_decode(params, CFG, jnp.array([7, 9]),
                                jnp.array([4, 4]), cache, bt)

        mesh = make_mesh({"tp": 8})
        sharded = shard_params(params, param_shardings(CFG, mesh))
        cache_sh = jax.device_put(init_kv_pages(CFG, NPAGES, PAGE, jnp.float32),
                                  kv_cache_shardings(CFG, mesh))
        _, cache_sh = forward_prefill(sharded, CFG, toks, pos, lens,
                                      cache_sh, bt)
        got, _ = forward_decode(sharded, CFG, jnp.array([7, 9]),
                                jnp.array([4, 4]), cache_sh, bt)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_indivisible_axis_falls_back_to_replication(self):
        tiny = llama3_tiny(dtype=jnp.float32)  # 2 kv heads vs 8-way mesh
        mesh = make_mesh({"tp": 8})
        # Flat projection dim (4 heads × 32 = 128) divides 8 → sharded.
        assert param_shardings(tiny, mesh)["layers"]["wq"].spec == \
            P(None, None, "tp")
        # KV-head axis (2) does not divide 8 → cache replicated.
        assert kv_cache_shardings(tiny, mesh)["k"].spec == \
            P(None, None, None, None)


class TestDPTrainStep:
    def test_sharded_train_step_runs(self):
        """The dp×tp train step dryrun_multichip exercises."""
        import optax

        mesh = make_mesh({"dp": 2, "tp": 4})
        cfg = llama3_tiny(dtype=jnp.float32, n_heads=4, n_kv_heads=4,
                          dim=32, ffn_dim=64, vocab_size=128)
        params = shard_params(init_params(jax.random.PRNGKey(0), cfg),
                              param_shardings(cfg, mesh))
        opt = optax.adamw(1e-3)
        opt_state = opt.init(params)

        def train_step(params, opt_state, tokens, cache, bt):
            l, g = jax.value_and_grad(loss_fn)(params, cfg, tokens, cache, bt)
            updates, opt_state = opt.update(g, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, l

        toks = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, 128),
            batch_sharding(mesh, 2))
        bt = jnp.stack([jnp.array([i * 2 + 1, i * 2 + 2], jnp.int32)
                        for i in range(4)])
        cache = init_kv_pages(cfg, 64, 4, jnp.float32)
        step = jax.jit(train_step)
        params2, opt_state, loss = step(params, opt_state, toks, cache, bt)
        assert jnp.isfinite(loss)
        # Param sharding preserved through the update.
        assert params2["layers"]["wq"].sharding.spec == P(None, None, "tp")


class TestTp8Llama70bShape:
    """BASELINE config #5 shape check: the 70B architecture's sharding
    factorisation (8 KV heads → tp=8 puts exactly ONE kv head per
    device; 64 q heads → 8 per device) compiles and matches the
    single-device forward on an 8-way tp mesh. Run at tiny dim with the
    REAL head/kv-head ratio so the PartitionSpecs exercised are the
    ones a v5e-16 70B deployment uses."""

    def test_tp8_forward_matches_single(self):
        # 70B ratios: 64 heads, 8 kv heads (n_rep=8); scaled-down dims.
        cfg = llama3_tiny(dtype=jnp.float32, n_heads=64, n_kv_heads=8,
                          dim=256, ffn_dim=512, vocab_size=256,
                          n_layers=2)
        params = init_params(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0,
                                  cfg.vocab_size)
        pos = jnp.broadcast_to(jnp.arange(8), (1, 8))
        lens = jnp.array([8])
        bt = jnp.array([[1, 2, 0, 0]], jnp.int32)

        ref_logits, ref_cache = forward_prefill(
            params, cfg, toks, pos, lens, init_kv_pages(cfg, NPAGES, PAGE),
            bt)

        mesh = make_mesh({"dp": 1, "tp": 8})
        sh_params = shard_params(params, param_shardings(cfg, mesh))
        sh_cache = jax.device_put(init_kv_pages(cfg, NPAGES, PAGE),
                                  kv_cache_shardings(cfg, mesh))
        # KV-head axis (dim 3 of (L, P, ps, H_kv, D)) sharded 8-ways:
        # one kv head per device.
        kv_spec = kv_cache_shardings(cfg, mesh)["k"].spec
        assert kv_spec[3] == "tp", kv_spec
        logits, cache = forward_prefill(sh_params, cfg, toks, pos, lens,
                                        sh_cache, bt)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(ref_logits),
                                   atol=2e-4, rtol=2e-4)
        # Decode step with sharded params over the tp=8-sharded cache.
        last = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        dec, _ = forward_decode(sh_params, cfg, last,
                                jnp.array([8], jnp.int32), cache, bt)
        ref_dec, _ = forward_decode(params, cfg, last,
                                    jnp.array([8], jnp.int32), ref_cache,
                                    bt)
        np.testing.assert_allclose(np.asarray(dec), np.asarray(ref_dec),
                                   atol=2e-4, rtol=2e-4)
