"""Radix-tree prefix KV cache (llmq_tpu/prefixcache/): ref-counted
block sharing, LRU/FIFO eviction with in-flight pinning, invalidation,
and engine integration — including the acceptance gates: a two-turn
conversation replay through the real (CPU-mode JAX) engine prefills
strictly fewer tokens on turn 2, decodes identically to the cache-off
path, and ``enabled: false`` restores exact pre-cache behavior."""

import jax
import jax.numpy as jnp
import pytest

from llmq_tpu.core.config import PrefixCacheConfig
from llmq_tpu.core.types import Priority
from llmq_tpu.engine.engine import GenRequest, InferenceEngine
from llmq_tpu.engine.executor import EchoExecutor, JaxExecutor
from llmq_tpu.engine.kv_allocator import PageAllocator
from llmq_tpu.engine.tokenizer import ByteTokenizer
from llmq_tpu.prefixcache import PrefixCache


# -- allocator ref-counting ----------------------------------------------------


class TestAllocatorRefcounts:
    def test_retain_free_lifecycle(self):
        a = PageAllocator(8, 16)
        pages = a.alloc(3)
        assert all(a.refcount(p) == 1 for p in pages)
        a.retain(pages)
        assert all(a.refcount(p) == 2 for p in pages)
        a.free(pages)                       # one holder left
        assert a.available() == 7 - 3
        a.free(pages)                       # last holder → pool
        assert a.available() == 7
        assert all(a.refcount(p) == 0 for p in pages)

    def test_double_free_raises(self):
        a = PageAllocator(8, 16)
        pages = a.alloc(1)
        a.free(pages)
        with pytest.raises(ValueError):
            a.free(pages)

    def test_retain_unallocated_raises(self):
        a = PageAllocator(8, 16)
        with pytest.raises(ValueError):
            a.retain([3])

    def test_shared_pages_stat(self):
        a = PageAllocator(8, 16)
        pages = a.alloc(2)
        assert a.shared_pages() == 0
        a.retain(pages[:1])
        assert a.shared_pages() == 1


# -- radix tree ----------------------------------------------------------------


def make_cache(num_pages=64, page_size=4, **kw):
    alloc = PageAllocator(num_pages, page_size)
    return alloc, PrefixCache(alloc, page_size, **kw)


def seq_pages(alloc, n):
    pages = alloc.alloc(n)
    assert pages is not None
    return pages


class TestRadixTree:
    def test_insert_then_match_shares_pages(self):
        alloc, pc = make_cache()
        ids = list(range(10))              # 2 full blocks + tail of 2
        pages = seq_pages(alloc, 3)
        assert pc.insert(ids, pages) == 2
        assert pc.pages == 2
        # The tree holds its own refs on the two full-block pages.
        assert alloc.refcount(pages[0]) == 2
        assert alloc.refcount(pages[2]) == 1     # tail not published
        m = pc.match(ids)
        assert m.length == 8 and m.pages == pages[:2]
        assert alloc.refcount(pages[0]) == 3     # tree + owner + match

    def test_match_leaves_at_least_one_token(self):
        alloc, pc = make_cache()
        ids = list(range(8))               # exactly 2 blocks
        pages = seq_pages(alloc, 2)
        pc.insert(ids, pages)
        m = pc.match(ids)                  # (8-1)//4 = 1 block max
        assert m.length == 4

    def test_miss_and_hit_counters(self):
        alloc, pc = make_cache()
        assert pc.match(list(range(9))).length == 0
        pages = seq_pages(alloc, 2)
        pc.insert(list(range(8)), pages)
        assert pc.match(list(range(9))).length == 8
        assert pc.hits == 1 and pc.misses == 1

    def test_duplicate_insert_keeps_existing_pages(self):
        alloc, pc = make_cache()
        ids = list(range(8))
        first = seq_pages(alloc, 2)
        pc.insert(ids, first)
        dup = seq_pages(alloc, 2)
        assert pc.insert(ids, dup) == 0            # nothing new cached
        assert alloc.refcount(dup[0]) == 1          # not adopted
        assert alloc.refcount(first[0]) == 2        # tree kept the original

    def test_divergence_forks_below_shared_prefix(self):
        """Two streams share block 0 then diverge: the tree holds one
        shared node plus two distinct children (COW at block
        granularity — nobody ever wrote a shared page)."""
        alloc, pc = make_cache()
        a = [1, 2, 3, 4, 10, 11, 12, 13]
        b = [1, 2, 3, 4, 20, 21, 22, 23]
        pa = seq_pages(alloc, 2)
        pc.insert(a, pa)
        # stream b matched block 0, re-used pa[0], wrote its own block 1
        m = pc.match(b)
        assert m.length == 4 and m.pages == [pa[0]]
        pb = seq_pages(alloc, 1)
        pc.insert(b, [pa[0], pb[0]])
        assert pc.pages == 3
        assert alloc.refcount(pa[0]) >= 3   # tree + owner a + matcher b
        assert alloc.refcount(pa[1]) == 2   # a's exclusive branch
        assert alloc.refcount(pb[0]) == 2   # b's exclusive branch

    def test_eviction_skips_locked_leaves(self):
        """Eviction racing an in-flight match: pinned pages survive."""
        alloc, pc = make_cache()
        ids = list(range(9))
        pages = seq_pages(alloc, 3)
        pc.insert(ids, pages)
        m = pc.match(ids)                   # locks both nodes
        assert pc.evict_pages(10) == 0      # everything pinned
        assert pc.pages == 2
        pc.unlock(m)
        alloc.free(m.pages)                 # matcher lets go
        alloc.free(pages)                   # original owner lets go
        assert pc.evict_pages(10) == 2      # now evictable, pages real-freed
        assert pc.pages == 0

    def test_lru_capacity_eviction(self):
        alloc, pc = make_cache(page_size=4, max_pages=2)
        old = seq_pages(alloc, 1)
        pc.insert([1, 2, 3, 4], old)
        alloc.free(old)                     # tree is sole owner
        new_pages = seq_pages(alloc, 2)
        pc.insert([9, 8, 7, 6, 5, 4, 3, 2], new_pages)
        alloc.free(new_pages)
        assert pc.pages == 2                # capacity held
        # the LRU entry (the first insert) was evicted
        assert pc.match([1, 2, 3, 4, 0]).length == 0

    def test_fifo_policy(self):
        alloc, pc = make_cache(page_size=4, policy="fifo", max_pages=2)
        a = seq_pages(alloc, 1)
        pc.insert([1, 2, 3, 4], a)
        b = seq_pages(alloc, 1)
        pc.insert([5, 6, 7, 8], b)
        # Touch the oldest so LRU would keep it; FIFO must not care.
        m = pc.match([1, 2, 3, 4, 0])
        pc.unlock(m)
        alloc.free(m.pages)
        c = seq_pages(alloc, 1)
        pc.insert([9, 10, 11, 12], c)
        assert pc.match([1, 2, 3, 4, 0]).length == 0   # first in, first out

    def test_bad_policy_rejected(self):
        alloc = PageAllocator(8, 4)
        with pytest.raises(ValueError):
            PrefixCache(alloc, 4, policy="random")

    def test_invalidate_prunes_exclusive_tail_only(self):
        """Conversation-delete semantics: the deleted stream's exclusive
        tail goes; a block shared with another stream (it has another
        child under it) survives."""
        alloc, pc = make_cache()
        a = [1, 2, 3, 4, 10, 11, 12, 13]
        b = [1, 2, 3, 4, 20, 21, 22, 23]
        pa = seq_pages(alloc, 2)
        pb = seq_pages(alloc, 2)
        pc.insert(a, pa)
        pc.insert(b, [pa[0], pb[1]])
        assert pc.pages == 3
        assert pc.invalidate(a) == 1        # only a's exclusive block
        assert pc.pages == 2
        assert pc.match(b + [0]).length == 8   # b's path fully intact

    def test_invalidate_all(self):
        alloc, pc = make_cache()
        pages = seq_pages(alloc, 2)
        pc.insert(list(range(8)), pages)
        alloc.free(pages)
        assert pc.invalidate_all() == 2
        assert pc.pages == 0 and alloc.available() == alloc.total


# -- engine integration (echo executor: page accounting) -----------------------


def make_echo_engine(**kw):
    tok = ByteTokenizer()
    ex = EchoExecutor(batch_size=2, page_size=4, num_pages=kw.pop("num_pages", 64),
                      max_pages_per_seq=16, eos_id=tok.eos_id)
    return InferenceEngine(ex, tok, enable_metrics=False,
                           max_decode_steps=8, **kw)


class TestEngineIntegration:
    def test_two_turn_replay_uses_cache(self):
        eng = make_echo_engine(prefix_cache=PrefixCacheConfig(enabled=True))
        h1 = eng.submit(GenRequest(id="t1", prompt="abcdefgh",
                                   conversation_id="c1"))
        eng.run_until_idle()
        assert h1.result.cached_tokens == 0
        before = eng.cached_prefill_tokens_total
        h2 = eng.submit(GenRequest(id="t2", prompt="ijkl",
                                   conversation_id="c1"))
        eng.run_until_idle()
        assert h2.result.cached_tokens > 0
        assert eng.cached_prefill_tokens_total > before

    def test_cross_conversation_radix_share(self):
        """Concurrent fork: two conversations share a prompt prefix then
        diverge — the second adopts the first's published pages."""
        eng = make_echo_engine(prefix_cache=PrefixCacheConfig(enabled=True))
        h1 = eng.submit(GenRequest(id="a", prompt="shared prefix! A tail",
                                   conversation_id="ca"))
        eng.run_until_idle()
        h2 = eng.submit(GenRequest(id="b", prompt="shared prefix! B tail",
                                   conversation_id="cb"))
        eng.run_until_idle()
        assert h1.result.finish_reason in ("eos", "length")
        assert h2.result.cached_tokens > 0          # radix hit, not conv pin
        assert eng.allocator.shared_pages() > 0
        st = eng.get_stats()["prefix_cache"]
        assert st["hits"] >= 1 and st["pages"] > 0

    def test_disabled_is_hard_off(self):
        eng = make_echo_engine()                     # default: no cache
        assert eng._prefix_cache is None
        eng.submit(GenRequest(id="a", prompt="abcd",
                              conversation_id="c"))
        eng.run_until_idle()
        assert "prefix_cache" not in eng.get_stats()
        assert eng.prefix_hits == 0 and eng.prefix_misses == 0
        cfg = PrefixCacheConfig(enabled=False)
        eng2 = make_echo_engine(prefix_cache=cfg)
        assert eng2._prefix_cache is None

    def test_pin_ttl_expiry_keeps_tree_prefix(self, fake_clock):
        """Losing the HBM pin (TTL) must NOT invalidate the radix tree —
        the tree is exactly the fallback that lets turn N+1 still reuse
        the prefix after its pin is reclaimed."""
        eng = make_echo_engine(
            prefix_cache=PrefixCacheConfig(enabled=True),
            kv_pin_ttl=5.0, clock=fake_clock)
        h = eng.submit(GenRequest(id="a", prompt="ttl survivor prompt",
                                  conversation_id="ct"))
        eng.run_until_idle()
        assert h.result.finish_reason in ("eos", "length")
        fake_clock.advance(10.0)
        eng.step()                                   # expires the pin
        assert "ct" not in eng.cached_conversations()
        assert eng.get_stats()["prefix_cache"]["pages"] > 0
        h2 = eng.submit(GenRequest(id="b", prompt="ttl survivor prompt",
                                   conversation_id="ct2"))
        eng.run_until_idle()
        assert h2.result.cached_tokens > 0           # served by the tree

    def test_delete_after_pin_expiry_still_invalidates(self, fake_clock):
        """The delete contract must hold even when the HBM pin was
        already reclaimed: the engine remembers the evicted stream and
        prunes the tree when the conversation is actually deleted."""
        eng = make_echo_engine(
            prefix_cache=PrefixCacheConfig(enabled=True),
            kv_pin_ttl=5.0, clock=fake_clock)
        h = eng.submit(GenRequest(id="a", prompt="expire then delete me",
                                  conversation_id="cx"))
        eng.run_until_idle()
        assert h.result.finish_reason in ("eos", "length")
        fake_clock.advance(10.0)
        eng.step()                                    # pin expires
        assert eng.get_stats()["prefix_cache"]["pages"] > 0
        eng.drop_conversation("cx")                   # actual delete
        assert eng.get_stats()["prefix_cache"]["pages"] == 0
        assert eng.allocator.used() == 0

    def test_delete_prunes_divergent_branches(self, fake_clock):
        """An expired pin followed by a no-history turn publishes a
        DIVERGENT branch (the re-prefilled turn echoes only its tail).
        Delete must prune every stream the conversation ever published,
        not just the newest."""
        eng = make_echo_engine(
            prefix_cache=PrefixCacheConfig(enabled=True),
            kv_pin_ttl=5.0, clock=fake_clock)
        eng.submit(GenRequest(id="a", prompt="drive the delete contract",
                              conversation_id="cm"))
        eng.run_until_idle()
        fake_clock.advance(10.0)
        eng.step()                          # pin expires; tree keeps blocks
        eng.submit(GenRequest(id="b", prompt="drive the delete contract",
                              conversation_id="cm"))
        eng.run_until_idle()                # turn-2 completes and re-pins
        eng.drop_conversation("cm")
        assert eng.get_stats()["prefix_cache"]["pages"] == 0
        assert eng.allocator.used() == 0

    def test_delete_mid_turn_with_radix_match_prunes_at_finish(
            self, fake_clock):
        """Delete arriving while a turn admitted via radix match is
        in flight: the finishing sequence must unlock its OWN match
        pins before pruning, or the invalidation no-ops against them."""
        eng = make_echo_engine(
            prefix_cache=PrefixCacheConfig(enabled=True),
            kv_pin_ttl=5.0, clock=fake_clock)
        h1 = eng.submit(GenRequest(id="a", prompt="mid turn delete case",
                                   conversation_id="cm"))
        eng.run_until_idle()
        assert h1.result.finish_reason in ("eos", "length")
        fake_clock.advance(10.0)
        eng.step()                          # pin expires; tree keeps blocks
        assert eng.get_stats()["prefix_cache"]["pages"] > 0
        h2 = eng.submit(GenRequest(id="b", prompt="mid turn delete case",
                                   conversation_id="cm"))
        for _ in range(3):
            eng.step()                      # admitted, matched, decoding
        assert h2.result is None            # still in flight
        eng.drop_conversation("cm")         # delete mid-turn
        eng.run_until_idle()
        assert h2.done
        assert eng.get_stats()["prefix_cache"]["pages"] == 0
        assert eng.allocator.used() == 0

    def test_conversation_delete_invalidates(self):
        eng = make_echo_engine(prefix_cache=PrefixCacheConfig(enabled=True))
        h = eng.submit(GenRequest(id="a", prompt="delete me soon",
                                  conversation_id="cd"))
        eng.run_until_idle()
        assert h.result.finish_reason in ("eos", "length")
        st = eng.get_stats()["prefix_cache"]
        assert st["pages"] > 0
        eng.drop_conversation("cd")
        st = eng.get_stats()["prefix_cache"]
        assert st["pages"] == 0                      # exclusive path pruned
        assert eng.allocator.used() == 0             # every ref released

    def test_pool_pressure_evicts_tree_not_inflight(self):
        """Pool exhaustion sheds zero-ref tree leaves; pages matched by
        an in-flight sequence are pinned and survive."""
        eng = make_echo_engine(
            num_pages=17,                            # 16 allocatable
            prefix_cache=PrefixCacheConfig(enabled=True))
        # Publish a prefix, then drop its conversation pin so only the
        # tree holds it.
        h = eng.submit(GenRequest(id="a", prompt="x" * 24,
                                  conversation_id="c1"))
        eng.run_until_idle()
        assert h.result.finish_reason in ("eos", "length")
        eng.touch_conversation("c1")
        # Second request fills the rest of the pool → pressure must
        # reclaim the conversation pin and/or tree pages, not deadlock.
        h2 = eng.submit(GenRequest(id="b", prompt="y" * 40,
                                   max_new_tokens=4))
        eng.run_until_idle()
        assert h2.result.finish_reason in ("eos", "length")

    def test_handle_recorded_in_state_manager(self):
        from llmq_tpu.conversation.state_manager import StateManager
        from llmq_tpu.core.config import ConversationConfig

        sm = StateManager(ConversationConfig(cleanup_interval=0))
        eng = make_echo_engine(prefix_cache=PrefixCacheConfig(enabled=True))
        eng.attach_conversation_manager(sm)
        sm.create(user_id="u", conversation_id="ch")
        h = eng.submit(GenRequest(id="a", prompt="handled prompt",
                                  conversation_id="ch"))
        eng.run_until_idle()
        assert h.result.finish_reason in ("eos", "length")
        handle = sm.prefix_handle("ch")
        assert handle is not None
        assert handle["length"] > 0 and handle["pages"] > 0

    def test_sweep_with_eviction_pressure_stays_consistent(self):
        """Randomized soak under a small pool: conversations, shared
        prompts, cancellations — at idle every page ref balances
        (used == pinned conversations + tree-only pages)."""
        import random

        rng = random.Random(7)
        eng = make_echo_engine(
            num_pages=33,
            prefix_cache=PrefixCacheConfig(enabled=True,
                                           max_cached_pages=8))
        prompts = ["common preamble " + str(i % 3) + " x" * rng.randrange(12)
                   for i in range(30)]
        handles = []
        for i, p in enumerate(prompts):
            conv = f"c{rng.randrange(5)}" if rng.random() < 0.5 else ""
            h = eng.submit(GenRequest(id=f"s{i}", prompt=p,
                                      conversation_id=conv,
                                      priority=rng.choice(list(Priority)),
                                      max_new_tokens=rng.randrange(1, 6)))
            handles.append(h)
            for _ in range(rng.randrange(3)):
                eng.step()
            if rng.random() < 0.1:
                rng.choice(handles).cancel()
        eng.run_until_idle()
        assert all(h.done for h in handles)
        st = eng.get_stats()
        assert st["prefix_cache"]["pages"] <= 8      # capacity respected
        # Every page still out of the pool is attributable: pinned
        # conversation KV or tree-cached (shared refs collapse — used
        # counts physical pages).
        for cid in list(eng.cached_conversations()):
            eng.drop_conversation(cid)
        eng._prefix_cache.invalidate_all()
        assert eng.allocator.used() == 0


# -- real-engine (CPU-mode JAX) acceptance -------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    from llmq_tpu.models.llama import init_params, llama3_tiny

    cfg = llama3_tiny(dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                      ffn_dim=128, vocab_size=512, max_seq_len=256)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def run_two_turns(cfg, params, prefix_cache, cache_dtype=None):
    tok = ByteTokenizer()
    ex = JaxExecutor(cfg, params, batch_size=2, page_size=8, num_pages=64,
                     prefill_buckets=[16, 64], eos_id=tok.eos_id,
                     chunk_size=4, cache_dtype=cache_dtype)
    eng = InferenceEngine(ex, tok, enable_metrics=False,
                          max_decode_steps=12, prefix_cache=prefix_cache)
    h1 = eng.submit(GenRequest(id="t1", prompt="the quick brown fox",
                               conversation_id="c", max_new_tokens=10))
    eng.run_until_idle()
    h2 = eng.submit(GenRequest(id="t2", prompt=" jumps over",
                               conversation_id="c", max_new_tokens=10))
    eng.run_until_idle()
    h3 = eng.submit(GenRequest(id="t3", prompt="the quick brown fox",
                               conversation_id="d", max_new_tokens=10))
    eng.run_until_idle()
    return eng, (h1, h2, h3)


class TestJaxAcceptance:
    def test_two_turn_replay_fewer_prefill_tokens_same_tokens(self,
                                                              tiny_model):
        cfg, params = tiny_model
        eng_on, on = run_two_turns(cfg, params,
                                   PrefixCacheConfig(enabled=True))
        eng_off, off = run_two_turns(cfg, params, None)
        # Turn 2 starts from turn 1's committed pages: strictly fewer
        # tokens prefilled than its full history (the cached prefix),
        # observable through the cached_prefill_tokens metric.
        assert eng_on.cached_prefill_tokens_total > 0
        assert on[1].result.cached_tokens > 0
        # Cross-conversation radix hit (same prompt, different conv):
        assert on[2].result.cached_tokens > 0
        assert off[2].result.cached_tokens == 0
        # Decode output must match the cache-off path exactly (greedy).
        for h_on, h_off in zip(on, off):
            assert h_on.result.tokens == h_off.result.tokens
        # Off-path engine shows no cache movement at all.
        assert eng_off.prefix_hits == 0 and eng_off.prefix_misses == 0

    def test_int8_kv_scale_pages_shared(self, tiny_model):
        """int8-KV path: per-page quantization scales live in pools
        indexed by the same page id as the KV — a radix-shared page
        shares its scales by construction, and decode through shared
        int8 pages matches the cache-off int8 run."""
        cfg, params = tiny_model
        import dataclasses
        cfg = dataclasses.replace(cfg, pallas=False)
        eng_on, on = run_two_turns(cfg, params,
                                   PrefixCacheConfig(enabled=True),
                                   cache_dtype=jnp.int8)
        eng_off, off = run_two_turns(cfg, params, None,
                                     cache_dtype=jnp.int8)
        assert set(eng_on.executor.cache) == {"k", "v", "k_scale",
                                              "v_scale"}
        assert on[1].result.cached_tokens > 0
        assert on[2].result.cached_tokens > 0       # radix share, int8
        for h_on, h_off in zip(on, off):
            assert h_on.result.tokens == h_off.result.tokens


# -- CPU-mode bench smoke (CI satellite) ---------------------------------------


class TestBenchSmoke:
    def test_two_turn_replay_hit_rate_positive(self, tiny_model):
        """The CI smoke: a two-turn conversation replay through the real
        engine must report prefix_cache_hit_rate > 0."""
        cfg, params = tiny_model
        eng, handles = run_two_turns(cfg, params,
                                     PrefixCacheConfig(enabled=True))
        st = eng.get_stats()["prefix_cache"]
        assert st["admission_hit_rate"] > 0
        assert st["cached_prefill_tokens"] > 0


# -- scheduler seam ------------------------------------------------------------


class TestCacheAwareScheduling:
    def test_tokens_discounted_by_estimator(self):
        from llmq_tpu.scheduling.resource_scheduler import (
            Resource, ResourceRequest, ResourceScheduler, ResourceType)

        sched = ResourceScheduler()
        sched.register_resource(Resource(
            id="r1", capabilities={"tpu"},
            capacity={ResourceType.TOKENS: 100.0}))
        # Without the estimator a 160-token request cannot fit.
        req = ResourceRequest(amounts={ResourceType.TOKENS: 160.0},
                              metadata={"conversation_id": "c",
                                        "prompt_tokens": 160})
        assert sched._try_allocate(req) is None
        # With 75% of the context expected cached, only 40 are charged.
        sched.set_prefill_estimator(lambda md: (120, 40))
        alloc = sched._try_allocate(req)
        assert alloc is not None
        r = sched.get_resource("r1")
        assert r.used[ResourceType.TOKENS] == pytest.approx(40.0)
        # Release refunds exactly what was charged.
        sched.release_allocation(alloc.id, alloc.token)
        assert r.used[ResourceType.TOKENS] == pytest.approx(0.0)

    def test_zero_information_estimate_charges_raw(self):
        """An estimator answering (anything, 0) — e.g. metadata without
        a prompt size — must not collapse the charge to ~1 token and
        disable admission control."""
        from llmq_tpu.scheduling.resource_scheduler import (
            Resource, ResourceRequest, ResourceScheduler, ResourceType)

        sched = ResourceScheduler()
        sched.register_resource(Resource(
            id="r1", capabilities=set(),
            capacity={ResourceType.TOKENS: 100.0}))
        sched.set_prefill_estimator(lambda md: (0, 0))
        assert sched._try_allocate(ResourceRequest(
            amounts={ResourceType.TOKENS: 160.0})) is None
        sched.set_prefill_estimator(lambda md: (500, 0))
        assert sched._try_allocate(ResourceRequest(
            amounts={ResourceType.TOKENS: 160.0})) is None

    def test_estimator_failure_falls_back_to_raw(self):
        from llmq_tpu.scheduling.resource_scheduler import (
            Resource, ResourceRequest, ResourceScheduler, ResourceType)

        sched = ResourceScheduler()
        sched.register_resource(Resource(
            id="r1", capabilities=set(),
            capacity={ResourceType.TOKENS: 100.0}))
        sched.set_prefill_estimator(
            lambda md: (_ for _ in ()).throw(RuntimeError("boom")))
        req = ResourceRequest(amounts={ResourceType.TOKENS: 60.0})
        alloc = sched._try_allocate(req)
        assert alloc is not None
        r = sched.get_resource("r1")
        assert r.used[ResourceType.TOKENS] == pytest.approx(60.0)

    def test_engine_prefill_estimate(self):
        eng = make_echo_engine(prefix_cache=PrefixCacheConfig(enabled=True))
        h = eng.submit(GenRequest(id="a", prompt="warm this conv up",
                                  conversation_id="ce"))
        eng.run_until_idle()
        assert h.result.finish_reason in ("eos", "length")
        cached, new = eng.prefill_estimate("ce", 10)
        assert cached > 0 and new == 10
        assert eng.prefill_estimate("missing", 10) == (0, 10)

    def test_prefill_estimate_uses_handle_after_pin_expiry(self,
                                                           fake_clock):
        """With the pin reclaimed, the estimate falls back to the
        conversation service's recorded handle (full blocks only) —
        the radix tree still serves those blocks."""
        from llmq_tpu.conversation.state_manager import StateManager
        from llmq_tpu.core.config import ConversationConfig

        sm = StateManager(ConversationConfig(cleanup_interval=0))
        eng = make_echo_engine(
            prefix_cache=PrefixCacheConfig(enabled=True),
            kv_pin_ttl=5.0, clock=fake_clock)
        eng.attach_conversation_manager(sm)
        sm.create(user_id="u", conversation_id="ch")
        h = eng.submit(GenRequest(id="a", prompt="persistent handle case",
                                  conversation_id="ch"))
        eng.run_until_idle()
        assert h.result.finish_reason in ("eos", "length")
        fake_clock.advance(10.0)
        eng.step()                          # pin expires
        cached, new = eng.prefill_estimate("ch", 7)
        handle = sm.prefix_handle("ch")
        ps = eng.spec.page_size
        assert cached == (handle["length"] // ps) * ps > 0
        assert new == 7
