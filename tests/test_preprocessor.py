"""Preprocessor tests.

Mirrors reference tests/preprocessor_test.go:25-149 (keyword promotion,
user_priority override, explicit-priority respect, metadata preservation,
realtime keywords, question/sentiment analysis)."""

from llmq_tpu.core.types import Message, Priority
from llmq_tpu.preprocessor import Preprocessor, analyze_message_content


class TestPriorityInference:
    def test_keyword_promotion_high(self):
        p = Preprocessor()
        m = p.process_message(Message(content="This is urgent, please handle"))
        assert m.priority == Priority.HIGH

    def test_keyword_promotion_realtime(self):
        p = Preprocessor()
        m = p.process_message(Message(content="emergency! respond right now"))
        assert m.priority == Priority.REALTIME

    def test_most_hits_wins(self):
        p = Preprocessor()
        m = p.process_message(Message(
            content="urgent important critical but also asap"))
        # 3 high hits vs 1 realtime hit → HIGH.
        assert m.priority == Priority.HIGH

    def test_explicit_priority_respected(self):
        # preprocessor.go:63-65: explicit non-default priority wins.
        p = Preprocessor()
        m = p.process_message(Message(content="urgent!!", priority=Priority.LOW))
        assert m.priority == Priority.LOW

    def test_user_priority_metadata_override(self):
        p = Preprocessor()
        m = p.process_message(Message(
            content="hello", metadata={"user_priority": 1}))
        assert m.priority == Priority.REALTIME

    def test_invalid_metadata_override_ignored(self):
        p = Preprocessor()
        m = p.process_message(Message(
            content="hello", metadata={"user_priority": "not-a-priority"}))
        assert m.priority == Priority.NORMAL

    def test_per_user_default(self):
        p = Preprocessor()
        p.set_user_priority("vip-user", Priority.HIGH)
        m = p.process_message(Message(content="hello", user_id="vip-user"))
        assert m.priority == Priority.HIGH
        assert p.remove_user_priority("vip-user")
        m2 = p.process_message(Message(content="hello", user_id="vip-user"))
        assert m2.priority == Priority.NORMAL

    def test_override_order_metadata_beats_user_default(self):
        p = Preprocessor()
        p.set_user_priority("u", Priority.LOW)
        m = p.process_message(Message(
            content="x", user_id="u", metadata={"user_priority": "high"}))
        assert m.priority == Priority.HIGH

    def test_no_keywords_stays_normal(self):
        p = Preprocessor()
        m = p.process_message(Message(content="just a plain question here"))
        assert m.priority == Priority.NORMAL

    def test_keyword_needs_word_boundary(self):
        p = Preprocessor()
        # "soonish" should not match "soon".
        m = p.process_message(Message(content="see you soonish"))
        assert m.priority == Priority.NORMAL


class TestContentAnalysis:
    def test_metadata_annotations(self):
        p = Preprocessor()
        m = p.process_message(Message(content="Why is this broken and awful?"))
        assert m.metadata["analyzed"] is True
        assert m.metadata["is_question"] is True
        assert m.metadata["sentiment"] == "negative"
        assert m.metadata["word_count"] == 6

    def test_positive_sentiment(self):
        p = Preprocessor()
        m = p.process_message(Message(content="this is great, thanks a lot"))
        assert m.metadata["sentiment"] == "positive"

    def test_existing_metadata_preserved(self):
        p = Preprocessor()
        m = p.process_message(Message(content="hi", metadata={"keep": "me"}))
        assert m.metadata["keep"] == "me"

    def test_analysis_disabled(self):
        p = Preprocessor(enable_content_analysis=False)
        m = p.process_message(Message(content="what?"))
        assert "sentiment" not in m.metadata
        assert m.metadata["analyzed"] is True

    def test_standalone_analysis_does_not_mutate(self):
        m = Message(content="urgent thing?")
        analysis = analyze_message_content(m)
        assert analysis["suggested_priority"] == int(Priority.HIGH)
        assert analysis["is_question"] is True
        assert "analyzed" not in m.metadata
        assert m.priority == Priority.NORMAL
