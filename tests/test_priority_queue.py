"""MultiLevelQueue tests — both backends.

Mirrors reference tests/priorityqueue_test.go:14-239 (push/pop/peek/stats
ordering) and adds coverage the reference lacks: FIFO tie-break proof,
tombstone expiry, requeue accounting."""

import pytest

from llmq_tpu.core.errors import (
    QueueEmptyError,
    QueueFullError,
    QueueNotFoundError,
)
from llmq_tpu.core.types import Message, MessageStatus, Priority
from llmq_tpu.queueing.priority_queue import MultiLevelQueue


@pytest.fixture
def mlq(fake_clock, queue_backend) -> MultiLevelQueue:
    return MultiLevelQueue(clock=fake_clock, backend=queue_backend)


class TestOrdering:
    def test_priority_order(self, mlq):
        mlq.create_queue("q")
        for i, p in enumerate([Priority.LOW, Priority.REALTIME,
                               Priority.NORMAL, Priority.HIGH]):
            mlq.push("q", Message(content=f"m{i}", priority=p))
        got = [mlq.pop("q").content for _ in range(4)]
        assert got == ["m1", "m3", "m2", "m0"]

    def test_fifo_within_priority(self, mlq):
        # (priority asc, FIFO) — reference queue.go:22-27.
        mlq.create_queue("q")
        for i in range(50):
            mlq.push("q", Message(content=str(i), priority=Priority.NORMAL))
        got = [mlq.pop("q").content for _ in range(50)]
        assert got == [str(i) for i in range(50)]

    def test_interleaved(self, mlq):
        mlq.create_queue("q")
        mlq.push("q", Message(content="n1", priority=Priority.NORMAL))
        mlq.push("q", Message(content="r1", priority=Priority.REALTIME))
        assert mlq.pop("q").content == "r1"
        mlq.push("q", Message(content="r2", priority=Priority.REALTIME))
        assert mlq.pop("q").content == "r2"
        assert mlq.pop("q").content == "n1"


class TestLifecycle:
    def test_capacity(self, mlq):
        mlq.create_queue("q", capacity=2)
        mlq.push("q", Message())
        mlq.push("q", Message())
        with pytest.raises(QueueFullError):
            mlq.push("q", Message())

    def test_unknown_queue(self, mlq):
        with pytest.raises(QueueNotFoundError):
            mlq.push("nope", Message())
        with pytest.raises(QueueNotFoundError):
            mlq.pop("nope")
        with pytest.raises(QueueNotFoundError):
            mlq.get_stats("nope")

    def test_empty_pop(self, mlq):
        mlq.create_queue("q")
        with pytest.raises(QueueEmptyError):
            mlq.pop("q")
        assert mlq.try_pop("q") is None

    def test_peek_does_not_remove(self, mlq):
        mlq.create_queue("q")
        mlq.push("q", Message(content="a"))
        assert mlq.peek("q").content == "a"
        assert mlq.size("q") == 1
        assert mlq.pop("q").content == "a"

    def test_create_queue_idempotent(self, mlq):
        mlq.create_queue("q", capacity=5)
        mlq.create_queue("q", capacity=99)  # no error, no reset
        mlq.push("q", Message())
        assert mlq.size("q") == 1

    def test_remove_queue(self, mlq):
        mlq.create_queue("q")
        mlq.push("q", Message())
        mlq.remove_queue("q")
        assert not mlq.has_queue("q")
        with pytest.raises(QueueNotFoundError):
            mlq.remove_queue("q")

    def test_status_transitions(self, mlq):
        mlq.create_queue("q")
        m = Message()
        mlq.push("q", m)
        assert m.status == MessageStatus.PENDING
        m2 = mlq.pop("q")
        assert m2.status == MessageStatus.PROCESSING
        mlq.complete_message("q", m2)
        assert m2.status == MessageStatus.COMPLETED


class TestStats:
    def test_accounting(self, mlq, fake_clock):
        # Stat transitions (reference queue.go:197-211).
        mlq.create_queue("q")
        a, b = Message(), Message()
        mlq.push("q", a)
        mlq.push("q", b)
        fake_clock.advance(4.0)
        a2 = mlq.pop("q")
        b2 = mlq.pop("q")
        mlq.complete_message("q", a2, process_time=1.0)
        mlq.fail_message("q", b2, process_time=2.0)
        s = mlq.get_stats("q")
        assert s.pending_count == 0
        assert s.processing_count == 0
        assert s.completed_count == 1
        assert s.failed_count == 1
        assert s.total_wait_time == pytest.approx(8.0)  # 4s each
        assert s.total_process_time == pytest.approx(3.0)
        assert s.avg_wait_time == pytest.approx(4.0)

    def test_all_stats(self, mlq):
        mlq.create_queue("a")
        mlq.create_queue("b")
        mlq.push("a", Message())
        stats = mlq.get_all_stats()
        assert stats["a"].pending_count == 1
        assert stats["b"].pending_count == 0

    def test_wait_time_attached_to_message(self, mlq, fake_clock):
        mlq.create_queue("q")
        mlq.push("q", Message())
        fake_clock.advance(2.5)
        m = mlq.pop("q")
        assert m.last_wait_time == pytest.approx(2.5)


class TestExpiry:
    def test_expire_older_than(self, mlq, fake_clock):
        mlq.create_queue("q")
        old = Message(content="old")
        mlq.push("q", old)
        fake_clock.advance(100.0)
        mlq.push("q", Message(content="new"))
        expired = mlq.expire_older_than("q", max_age=50.0)
        assert [m.content for m in expired] == ["old"]
        assert old.status == MessageStatus.TIMEOUT
        assert mlq.size("q") == 1
        assert mlq.pop("q").content == "new"
        assert mlq.get_stats("q").failed_count == 1

    def test_peek_skips_tombstones(self, mlq, fake_clock):
        mlq.create_queue("q")
        mlq.push("q", Message(content="old", priority=Priority.REALTIME))
        fake_clock.advance(100.0)
        mlq.push("q", Message(content="new"))
        mlq.expire_older_than("q", max_age=50.0)
        assert mlq.peek("q").content == "new"


class TestRequeue:
    def test_requeue_keeps_stats_clean(self, mlq):
        mlq.create_queue("q")
        m = Message()
        mlq.push("q", m)
        popped = mlq.pop("q")
        mlq.requeue("q", popped)
        s = mlq.get_stats("q")
        assert s.pending_count == 1
        assert s.processing_count == 0
        assert s.completed_count == 0 and s.failed_count == 0
        assert mlq.pop("q").id == m.id


class TestLazyExtraction:
    """pop_handle/discard are O(1) LAZY deletions in both cores
    (the tenancy fair-dequeue extraction op, docs/tenancy.md): the
    item leaves the liveness index immediately while its heap entry
    stays behind as a stale record. pop/peek/pop_if must skip stale
    entries, and size/capacity must track liveness, not heap length."""

    ERR_EMPTY = -3

    @pytest.fixture
    def core(self, queue_backend):
        if queue_backend == "python":
            from llmq_tpu.queueing.priority_queue import _PyBackend
            return _PyBackend()
        from llmq_tpu.native.loader import NativeMLQ
        return NativeMLQ()

    def test_pop_skips_extracted_entries(self, core):
        core.create_queue("q", 0)
        for h in (1, 2, 3, 4):
            core.push("q", h, 1, 0.0)
        err, wait = core.pop_handle("q", 2, 5.0)
        assert err == 0 and wait == 5.0
        assert [core.pop("q", 5.0)[1] for _ in range(3)] == [1, 3, 4]
        assert core.pop("q", 5.0)[0] == self.ERR_EMPTY

    def test_peek_and_pop_if_skip_stale_top(self, core):
        core.create_queue("q", 0)
        core.push("q", 1, 1, 0.0)    # heap top
        core.push("q", 2, 1, 0.0)
        assert core.pop_handle("q", 1, 0.0)[0] == 0
        assert core.peek("q") == (0, 2)
        assert core.pop_if("q", 2, 0.0) == 0
        assert core.peek("q")[0] == self.ERR_EMPTY

    def test_extract_missing_handle_is_empty(self, core):
        core.create_queue("q", 0)
        core.push("q", 1, 1, 0.0)
        assert core.pop_handle("q", 99, 0.0)[0] == self.ERR_EMPTY
        assert core.pop_handle("q", 1, 0.0)[0] == 0
        # Already extracted — the stale heap entry is not re-poppable.
        assert core.pop_handle("q", 1, 0.0)[0] == self.ERR_EMPTY
        assert core.discard("q", 1) == self.ERR_EMPTY

    def test_capacity_and_size_track_liveness(self, core):
        core.create_queue("q", 2)
        assert core.push("q", 1, 1, 0.0) == 0
        assert core.push("q", 2, 1, 0.0) == 0
        assert core.push("q", 3, 1, 0.0) == -2          # ERR_FULL
        assert core.pop_handle("q", 1, 0.0)[0] == 0
        assert core.size("q") == 1
        # The stale heap entry must not count against capacity.
        assert core.push("q", 3, 1, 0.0) == 0
        assert core.size("q") == 2
        assert [core.pop("q", 0.0)[1] for _ in range(2)] == [2, 3]

    def test_discard_is_lazy_too(self, core):
        core.create_queue("q", 0)
        for h in (1, 2, 3):
            core.push("q", h, 1, 0.0)
        assert core.discard("q", 2) == 0
        assert core.size("q") == 2
        assert [core.pop("q", 0.0)[1] for _ in range(2)] == [1, 3]
