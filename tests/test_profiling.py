"""Tracing/profiling subsystem (SURVEY.md §5 — real code here, unlike
the reference's docs-only pprof/Jaeger recipes)."""

import json
import os

import pytest

from llmq_tpu.utils.profiling import SpanRecorder, annotate, trace


class TestSpanRecorder:
    def test_span_and_summary(self):
        rec = SpanRecorder()
        with rec.span("queue.pop"):
            pass
        with rec.span("queue.pop"):
            pass
        with rec.span("engine.decode_chunk", active=3):
            pass
        s = rec.summary()
        assert s["queue.pop"]["count"] == 2
        assert s["engine.decode_chunk"]["count"] == 1
        assert s["engine.decode_chunk"]["mean_ms"] >= 0

    def test_capacity_bound(self):
        rec = SpanRecorder(capacity=10)
        for i in range(50):
            rec.record(f"s{i}", 0.0, 0.001)
        assert len(rec.snapshot()) == 10
        assert rec.snapshot()[-1].name == "s49"

    def test_chrome_trace_dump(self, tmp_path):
        rec = SpanRecorder()
        with rec.span("a", foo=1):
            pass
        p = tmp_path / "trace.json"
        rec.dump_chrome_trace(str(p))
        data = json.loads(p.read_text())
        assert data["traceEvents"][0]["name"] == "a"
        assert data["traceEvents"][0]["args"] == {"foo": 1}

    def test_clear(self):
        rec = SpanRecorder()
        with rec.span("x"):
            pass
        rec.clear()
        assert rec.snapshot() == []

    def test_annotate_propagates_body_errors(self):
        with pytest.raises(ValueError, match="original"):
            with annotate("x"):
                raise ValueError("original")

    def test_concurrent_record_snapshot_clear(self):
        """The multi-worker serve path has N dispatch threads recording
        spans while the API stats route snapshots/summarizes and admin
        paths clear — all four must interleave without losing the lock
        discipline (no RuntimeError from mutating the deque mid-copy,
        no torn summaries, ring bound respected throughout)."""
        import threading
        import time as _time

        rec = SpanRecorder(capacity=256)
        stop = threading.Event()
        errors = []

        def worker(i):
            n = 0
            try:
                while not stop.is_set():
                    with rec.span(f"dispatch.{i}", seq=n):
                        pass
                    rec.record("engine.decode_chunk", 0.0, 0.001,
                               {"w": i})
                    n += 1
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def reader():
            try:
                while not stop.is_set():
                    snap = rec.snapshot()
                    assert len(snap) <= 256
                    summ = rec.summary()
                    for d in summ.values():
                        assert d["count"] >= 1
                    len(rec)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def clearer():
            try:
                while not stop.is_set():
                    _time.sleep(0.01)
                    rec.clear()
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = ([threading.Thread(target=worker, args=(i,))
                    for i in range(4)]
                   + [threading.Thread(target=reader) for _ in range(2)]
                   + [threading.Thread(target=clearer)])
        for t in threads:
            t.start()
        _time.sleep(0.4)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        assert not errors, errors
        assert len(rec.snapshot()) <= 256


class TestDeviceTrace:
    def test_noop_without_env(self, monkeypatch):
        monkeypatch.delenv("LLMQ_TRACE_DIR", raising=False)
        with trace("unit"):
            x = 1 + 1
        assert x == 2

    def test_writes_trace_dir(self, tmp_path, monkeypatch):
        monkeypatch.setenv("LLMQ_TRACE_DIR", str(tmp_path))
        import jax
        import jax.numpy as jnp
        with trace("unit"):
            jnp.zeros(8).block_until_ready()
        out = tmp_path / "unit"
        assert out.exists()
        # jax.profiler writes a plugins/profile tree with trace files.
        found = [f for _, _, fs in os.walk(out) for f in fs]
        assert found, "profiler produced no files"

    def test_engine_stats_include_profile(self):
        from llmq_tpu.engine import EchoExecutor, InferenceEngine
        from llmq_tpu.engine.tokenizer import ByteTokenizer
        tok = ByteTokenizer()
        ex = EchoExecutor(batch_size=2, eos_id=tok.eos_id)
        eng = InferenceEngine(ex, tok, enable_metrics=False)
        from llmq_tpu.engine.engine import GenRequest
        h = eng.submit(GenRequest(id="r1", prompt="hi", max_new_tokens=4))
        eng.run_until_idle()
        assert h.done
        stats = eng.get_stats()
        assert "engine.prefill" in stats["profile"]

    def test_explicit_dir_overrides_missing_env(self, tmp_path,
                                                monkeypatch):
        # The on-demand profile endpoint path: no ambient LLMQ_TRACE_DIR,
        # capture goes where the caller says.
        monkeypatch.delenv("LLMQ_TRACE_DIR", raising=False)
        import jax.numpy as jnp
        with trace("ondemand", dir=str(tmp_path)):
            jnp.zeros(4).block_until_ready()
        assert (tmp_path / "ondemand").exists()

    def test_annotate_active_and_noop_paths_on_cpu(self, monkeypatch):
        # Active path: a real TraceAnnotation on the CPU backend is a
        # harmless no-op region — the body must run exactly once.
        ran = []
        with annotate("cpu-region"):
            ran.append(1)
        assert ran == [1]
        # No-op path: annotation construction failing must not lose
        # the body (the endpoint on a backend without profiler support).
        import jax
        monkeypatch.setattr(jax.profiler, "TraceAnnotation",
                            lambda name: (_ for _ in ()).throw(
                                RuntimeError("no profiler")))
        ran = []
        with annotate("fallback-region"):
            ran.append(1)
        assert ran == [1]


class TestOnDemandProfile:
    def _server(self):
        from llmq_tpu.api.server import ApiServer
        from llmq_tpu.core.config import default_config
        return ApiServer(default_config())

    def test_single_flight_409_then_released(self, tmp_path,
                                             monkeypatch):
        """POST /api/v1/admin/profile: 202 with the trace path; a
        concurrent capture 409s; once the capture finishes the flight
        is released and the trace dir is readable (the acceptance
        criterion's single-flight contract). The output location is
        server-controlled (LLMQ_TRACE_DIR / tempdir) — a request-body
        path would be an arbitrary-write primitive."""
        import json as _json
        import time as _time

        from llmq_tpu.observability import device
        monkeypatch.setenv("LLMQ_TRACE_DIR", str(tmp_path))
        api = self._server()
        body = _json.dumps({"duration_ms": 100, "label": "t409",
                            "dir": "/definitely/not/honored"}).encode()
        status, out, _ = api.dispatch("POST", "/api/v1/admin/profile",
                                      body)
        assert status == 202, out
        # Body "dir" ignored; capture lands under the operator's dir.
        assert out["path"].startswith(str(tmp_path))
        status2, out2, _ = api.dispatch("POST", "/api/v1/admin/profile",
                                        b"{}")
        assert status2 == 409
        assert "already running" in out2["error"]
        # Bounded wait for release (profiler session start/stop on CPU
        # costs seconds; the capture itself is 100 ms).
        deadline = _time.time() + 60
        while _time.time() < deadline:
            if not device.profile_status()["active"]:
                break
            _time.sleep(0.1)
        st = device.profile_status()
        assert not st["active"], "capture never released the flight"
        assert st["last"]["label"] == "t409"
        found = [f for _, _, fs in os.walk(out["path"]) for f in fs]
        assert found, "on-demand capture produced no trace files"
        # Flight released: a new capture is accepted again.
        status3, out3, _ = api.dispatch(
            "POST", "/api/v1/admin/profile",
            _json.dumps({"duration_ms": 10}).encode())
        assert status3 == 202, out3
        deadline = _time.time() + 60
        while _time.time() < deadline:
            if not device.profile_status()["active"]:
                break
            _time.sleep(0.1)
        assert not device.profile_status()["active"]

    def test_bad_duration_is_400(self):
        api = self._server()
        status, out, _ = api.dispatch(
            "POST", "/api/v1/admin/profile",
            b'{"duration_ms": "soon"}')
        assert status == 400

    def test_status_route_reports_idle(self):
        api = self._server()
        status, out, _ = api.dispatch("GET", "/api/v1/admin/profile",
                                      b"")
        assert status == 200
        assert out["active"] in (False, True)
