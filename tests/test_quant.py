"""int8 w8a8 quantization: parity against the bf16 model, sharding
congruence, and footprint math (VERDICT r2 #1 — the path that fits
llama3-8B on a 16 GB chip)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmq_tpu.models.llama import (
    forward_decode,
    forward_prefill,
    get_config,
    init_kv_pages,
    init_params,
    llama3_tiny,
)
from llmq_tpu.ops.quant import (
    dequantize_weight,
    embed_lookup,
    is_quantized,
    params_bytes,
    qdot,
    quantize_embedding,
    quantize_params,
    quantize_weight,
)

CFG = llama3_tiny(dtype=jnp.float32, tie_embeddings=False)
PAGE, NPAGES, MAXP = 4, 64, 8


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def qparams(params):
    return quantize_params(params)


class TestLeafOps:
    def test_roundtrip_error_small(self):
        w = jax.random.normal(jax.random.PRNGKey(1), (64, 32), jnp.float32)
        qw = quantize_weight(w)
        back = dequantize_weight(qw, jnp.float32)
        # int8 symmetric per-channel: max error is half a quant step.
        step = np.asarray(qw["s"]).max()
        assert np.abs(np.asarray(back - w)).max() <= step * 0.51

    def test_qdot_close_to_dense(self):
        k1, k2 = jax.random.split(jax.random.PRNGKey(2))
        x = jax.random.normal(k1, (8, 64), jnp.float32)
        w = jax.random.normal(k2, (64, 32), jnp.float32)
        y = qdot(x, quantize_weight(w))
        ref = x @ w
        rel = np.linalg.norm(np.asarray(y - ref)) / np.linalg.norm(np.asarray(ref))
        assert rel < 0.02

    def test_embed_lookup_and_scale_shape(self):
        e = jax.random.normal(jax.random.PRNGKey(3), (16, 8), jnp.float32)
        qe = quantize_embedding(e)
        assert qe["q"].dtype == jnp.int8 and qe["s"].shape == (16, 1)
        toks = jnp.asarray([0, 5, 15])
        got = embed_lookup(qe, toks, jnp.float32)
        assert np.allclose(np.asarray(got), np.asarray(e[toks]), atol=0.05)

    def test_int8_native_dot_dtype(self):
        # The MXU path: int8 x int8 must accumulate in int32, not float.
        a = jnp.ones((4, 8), jnp.int8)
        b = jnp.ones((8, 4), jnp.int8)
        out = jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        assert out.dtype == jnp.int32 and int(out[0, 0]) == 8


class TestPytree:
    def test_quantize_params_structure(self, params, qparams):
        assert is_quantized(qparams["embed"])
        assert is_quantized(qparams["lm_head"])
        for name in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
            leaf = qparams["layers"][name]
            assert is_quantized(leaf), name
            assert leaf["q"].dtype == jnp.int8
            assert leaf["q"].shape == params["layers"][name].shape
            # scale keeps the contraction dim as 1
            assert leaf["s"].shape[-2] == 1
        # norms untouched
        assert qparams["layers"]["attn_norm"].dtype == params["layers"]["attn_norm"].dtype

    def test_idempotent(self, qparams):
        again = quantize_params(qparams)
        assert again["layers"]["wq"]["q"] is qparams["layers"]["wq"]["q"]

    def test_footprint_halved_vs_f32(self, params, qparams):
        # f32 tiny params → int8 should be ~1/4 the bytes (scales add <2%).
        assert params_bytes(qparams) < params_bytes(params) * 0.30


class TestForwardParity:
    """Quantized forward must track the bf16/f32 model closely enough to
    serve: high top-1 agreement and high logit cosine similarity."""

    def _run_prefill(self, p, cache):
        B, T = 2, 12
        rng = np.random.default_rng(0)
        toks = jnp.asarray(rng.integers(0, CFG.vocab_size, (B, T)), jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(T), (B, T)).astype(jnp.int32)
        lens = jnp.asarray([T, T], jnp.int32)
        bt = np.zeros((B, MAXP), np.int32)
        bt[0, :3] = [1, 2, 3]
        bt[1, :3] = [4, 5, 6]
        return forward_prefill(p, CFG, toks, pos, lens, cache,
                               jnp.asarray(bt))

    def test_prefill_parity(self, params, qparams):
        logits_f, _ = self._run_prefill(params, init_kv_pages(CFG, NPAGES, PAGE, dtype=jnp.float32))
        logits_q, _ = self._run_prefill(qparams, init_kv_pages(CFG, NPAGES, PAGE, dtype=jnp.float32))
        lf = np.asarray(logits_f).reshape(-1, CFG.vocab_size)
        lq = np.asarray(logits_q).reshape(-1, CFG.vocab_size)
        cos = np.sum(lf * lq, -1) / (
            np.linalg.norm(lf, axis=-1) * np.linalg.norm(lq, axis=-1) + 1e-9)
        assert cos.min() > 0.99, f"cosine {cos.min()}"
        agree = (lf.argmax(-1) == lq.argmax(-1)).mean()
        assert agree >= 0.9, f"top-1 agreement {agree}"

    def test_decode_parity(self, params, qparams):
        cache_f = init_kv_pages(CFG, NPAGES, PAGE, dtype=jnp.float32)
        cache_q = init_kv_pages(CFG, NPAGES, PAGE, dtype=jnp.float32)
        _, cache_f = self._run_prefill(params, cache_f)
        _, cache_q = self._run_prefill(qparams, cache_q)
        B, T = 2, 12
        toks = jnp.asarray([7, 9], jnp.int32)
        pos = jnp.asarray([T, T], jnp.int32)
        bt = np.zeros((B, MAXP), np.int32)
        bt[0, :4] = [1, 2, 3, 7]
        bt[1, :4] = [4, 5, 6, 8]
        lf, _ = forward_decode(params, CFG, toks, pos, cache_f, jnp.asarray(bt))
        lq, _ = forward_decode(qparams, CFG, toks, pos, cache_q, jnp.asarray(bt))
        lf, lq = np.asarray(lf), np.asarray(lq)
        cos = np.sum(lf * lq, -1) / (
            np.linalg.norm(lf, axis=-1) * np.linalg.norm(lq, axis=-1) + 1e-9)
        assert cos.min() > 0.99

    def test_tied_embeddings_parity(self):
        cfg = llama3_tiny(dtype=jnp.float32, tie_embeddings=True)
        p = init_params(jax.random.PRNGKey(5), cfg)
        qp = quantize_params(p)
        B, T = 1, 8
        toks = jnp.asarray(np.arange(T)[None, :], jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(T), (B, T)).astype(jnp.int32)
        lens = jnp.asarray([T], jnp.int32)
        bt = np.zeros((B, MAXP), np.int32)
        bt[0, :2] = [1, 2]
        cache = init_kv_pages(cfg, NPAGES, PAGE, dtype=jnp.float32)
        cache2 = init_kv_pages(cfg, NPAGES, PAGE, dtype=jnp.float32)
        lf, _ = forward_prefill(p, cfg, toks, pos, lens, cache, jnp.asarray(bt))
        lq, _ = forward_prefill(qp, cfg, toks, pos, lens, cache2, jnp.asarray(bt))
        lf = np.asarray(lf).reshape(-1, cfg.vocab_size)
        lq = np.asarray(lq).reshape(-1, cfg.vocab_size)
        cos = np.sum(lf * lq, -1) / (
            np.linalg.norm(lf, axis=-1) * np.linalg.norm(lq, axis=-1) + 1e-9)
        assert cos.min() > 0.99


class TestSharded:
    def test_quantized_tp_forward_matches_single(self, qparams):
        """int8 model under an 8-way tp mesh == single-device run."""
        from jax.sharding import Mesh
        from llmq_tpu.parallel.sharding import (param_shardings,
                                                shard_params)

        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs 8 virtual devices")
        mesh = Mesh(np.array(devs[:8]).reshape(8), ("tp",))
        shardings = param_shardings(CFG, mesh, quantized=True)
        # Trees must be congruent — this throws on mismatch.
        sharded = shard_params(qparams, shardings)

        B, T = 2, 12
        rng = np.random.default_rng(0)
        toks = jnp.asarray(rng.integers(0, CFG.vocab_size, (B, T)), jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(T), (B, T)).astype(jnp.int32)
        lens = jnp.asarray([T, T], jnp.int32)
        bt = np.zeros((B, MAXP), np.int32)
        bt[0, :3] = [1, 2, 3]
        bt[1, :3] = [4, 5, 6]
        cache1 = init_kv_pages(CFG, NPAGES, PAGE, dtype=jnp.float32)
        cache2 = init_kv_pages(CFG, NPAGES, PAGE, dtype=jnp.float32)
        with mesh:
            ls, _ = forward_prefill(sharded, CFG, toks, pos, lens, cache2,
                                    jnp.asarray(bt))
        l1, _ = forward_prefill(qparams, CFG, toks, pos, lens, cache1,
                                jnp.asarray(bt))
        assert np.allclose(np.asarray(ls), np.asarray(l1), atol=2e-2)


class TestSizing:
    def test_8b_int8_fits_v5e(self):
        """The point of the exercise: 8B int8 + KV pool < 16 GB HBM."""
        cfg = get_config("llama3-8b")
        p8 = 8.03e9  # params
        int8_bytes = p8 * 1.0
        kv_per_tok = 2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim * 2
        kv_pool = 16 * 1024 * kv_per_tok  # 16 seqs x 1024 ctx, bf16
        assert int8_bytes + kv_pool < 15.5e9
        assert 2 * p8 > 16e9  # and bf16 provably does NOT fit


class TestInt8KVCache:
    """int8 KV pools (per-token-per-head scales) vs the bf16 cache:
    same model, same inputs — logits must agree within quantization
    tolerance through prefill, continuation, and decode."""

    def _setup(self, dtype):
        from llmq_tpu.models.llama import (get_config, init_kv_pages,
                                           init_params)
        cfg = get_config("llama3-tiny", max_seq_len=128, pallas=False,
                         n_kv_heads=2)
        params = init_params(jax.random.PRNGKey(1), cfg)
        B, pages_per_seq, page = 2, 8, 16
        cache = init_kv_pages(cfg, B * pages_per_seq + 1, page,
                              dtype=dtype)
        bt = np.zeros((B, pages_per_seq), np.int32)
        n = 1
        for b in range(B):
            for p in range(pages_per_seq):
                bt[b, p] = n
                n += 1
        return cfg, params, cache, jnp.asarray(bt)

    def test_prefill_and_decode_match_bf16(self):
        from llmq_tpu.models.llama import (forward_decode,
                                           forward_prefill)

        T = 24
        toks = jax.random.randint(jax.random.PRNGKey(2), (2, T), 5, 500,
                                  jnp.int32)
        positions = jnp.broadcast_to(jnp.arange(T), (2, T))
        lengths = jnp.full((2,), T, jnp.int32)

        outs = {}
        for name, dt in (("bf16", None), ("int8", jnp.int8)):
            cfg, params, cache, bt = self._setup(dt)
            if name == "int8":
                assert set(cache) == {"k", "v", "k_scale", "v_scale"}
            logits, cache = forward_prefill(params, cfg, toks, positions,
                                            lengths, cache, bt)
            # one decode step on top of the prefilled history
            last = toks[:, -1]
            pos = jnp.full((2,), T, jnp.int32)
            dlogits, cache = forward_decode(params, cfg, last, pos, cache,
                                            bt)
            outs[name] = (np.asarray(logits), np.asarray(dlogits))

        # int8 KV quantization error is ~0.5% per value; logits are
        # sums over D=32 — tolerance is loose but far below the
        # bf16-vs-int8-weights gap that would indicate a real bug.
        p_ref, d_ref = outs["bf16"]
        p_q, d_q = outs["int8"]
        ref_scale = np.abs(p_ref).max()
        assert np.abs(p_q - p_ref).max() < 0.05 * ref_scale, (
            np.abs(p_q - p_ref).max(), ref_scale)
        assert np.abs(d_q - d_ref).max() < 0.05 * np.abs(d_ref).max()

    def test_int8_cache_layout(self):
        from llmq_tpu.models.llama import init_kv_pages, llama3_tiny
        cfg = llama3_tiny(n_kv_heads=2)
        c = init_kv_pages(cfg, 9, 16, dtype=jnp.int8)
        assert c["k"].dtype == jnp.int8
        assert c["k_scale"].shape == (cfg.n_layers, 9, 2, 16)
        assert c["k_scale"].dtype == jnp.bfloat16

    def test_build_engine_int8_kv_generates(self):
        """config.model.kv_quantization='int8' through build_engine:
        pools carry scale leaves and generation + turn-2 KV reuse work
        (CPU, tiny model — the serving wiring, not the kernel)."""
        from llmq_tpu.core.config import default_config
        from llmq_tpu.engine import build_engine

        cfg = default_config()
        cfg.executor.backend = "jax"
        cfg.model.name = "llama3-tiny"
        cfg.model.max_seq_len = 128
        cfg.model.kv_quantization = "int8"
        cfg.executor.max_batch_size = 2
        cfg.executor.page_size = 16
        cfg.executor.kv_pages = 17
        cfg.executor.prefill_buckets = [16]
        cfg.executor.decode_chunk = 4
        eng = build_engine(cfg, warmup=False)
        assert "k_scale" in eng.executor.cache
        eng.start()
        try:
            r1 = eng.generate("hi there", max_new_tokens=4,
                              conversation_id="c")
            assert r1.tokens
            r2 = eng.generate(" again", max_new_tokens=4,
                              conversation_id="c")
            assert r2.cached_tokens > 0
        finally:
            eng.stop()
