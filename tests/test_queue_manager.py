"""QueueManager tests.

Mirrors reference tests/priorityqueue_test.go:241-363 (manager single +
batch ops, complete/fail accounting) plus new coverage: tier routing (the
reference has a latent ErrQueueNotFound bug here, SURVEY.md #16), scale
signals, real stale cleanup."""

import pytest

from llmq_tpu.core.config import default_config
from llmq_tpu.core.types import Message, Priority
from llmq_tpu.queueing.queue_manager import (
    PriorityAdjustRule,
    QueueManager,
)


@pytest.fixture
def manager(fake_clock, queue_backend) -> QueueManager:
    return QueueManager("test", clock=fake_clock, backend=queue_backend,
                        enable_metrics=False)


class TestRouting:
    def test_tier_queues_exist(self, manager):
        assert set(manager.queue_names()) == {"realtime", "high", "normal", "low"}

    def test_routes_by_priority(self, manager):
        m = Message(priority=Priority.REALTIME)
        qname = manager.push_message(m)
        assert qname == "realtime"
        assert manager.queue.size("realtime") == 1

    def test_explicit_queue(self, manager):
        manager.create_queue("custom")
        manager.push_message(Message(), "custom")
        assert manager.queue.size("custom") == 1


class TestRules:
    def test_rule_applied_before_push(self, manager):
        manager.add_priority_rule(PriorityAdjustRule(
            name="boost", condition=lambda m: "urgent" in m.content,
            target_priority=Priority.REALTIME))
        m = Message(content="this is urgent", priority=Priority.LOW)
        qname = manager.push_message(m)
        assert m.priority == Priority.REALTIME
        assert qname == "realtime"

    def test_rule_removal(self, manager):
        manager.add_priority_rule(PriorityAdjustRule(
            name="r", condition=lambda m: True, target_priority=Priority.LOW))
        assert manager.remove_priority_rule("r")
        assert not manager.remove_priority_rule("r")
        m = Message(priority=Priority.HIGH)
        manager.push_message(m)
        assert m.priority == Priority.HIGH


class TestBatchOps:
    def test_batch_push_pop(self, manager):
        msgs = [Message(priority=Priority.NORMAL) for _ in range(5)]
        manager.batch_push(msgs)
        out = manager.batch_pop("normal", 3)
        assert len(out) == 3
        assert manager.queue.size("normal") == 2

    def test_drain_in_priority_order(self, manager):
        # The strict-priority drain of cmd/queue-manager/main.go:112-124.
        manager.push_message(Message(content="low", priority=Priority.LOW))
        manager.push_message(Message(content="rt", priority=Priority.REALTIME))
        manager.push_message(Message(content="hi", priority=Priority.HIGH))
        out = manager.drain_in_priority_order(10)
        assert [m.content for m in out] == ["rt", "hi", "low"]


class TestAccounting:
    def test_complete_uses_tracked_queue(self, manager):
        m = Message(priority=Priority.HIGH)
        manager.push_message(m)
        popped = manager.pop_message("high")
        manager.complete_message(popped, process_time=0.5)
        s = manager.get_stats("high")
        assert s.completed_count == 1 and s.processing_count == 0

    def test_fail(self, manager):
        m = Message(priority=Priority.LOW)
        manager.push_message(m)
        manager.pop_message("low")
        manager.fail_message(m)
        assert manager.get_stats("low").failed_count == 1

    def test_requeue_message(self, manager):
        m = Message()
        manager.push_message(m)
        manager.pop_message("normal")
        manager.requeue_message(m)
        s = manager.get_stats("normal")
        assert s.pending_count == 1 and s.processing_count == 0


class TestMonitor:
    def test_scale_up_signal(self, fake_clock, queue_backend):
        signals = []
        cfg = default_config()
        cfg.scheduler.scale_up_threshold = 3
        cfg.scheduler.scale_down_threshold = 0
        qm = QueueManager("t", config=cfg, clock=fake_clock,
                          backend=queue_backend, enable_metrics=False,
                          scale_callback=signals.append)
        for _ in range(4):
            qm.push_message(Message())
        sig = qm.run_monitor_once()
        assert sig is not None and sig.direction == "up"
        assert signals and signals[0].total_pending == 4

    def test_scale_down_signal(self, fake_clock, queue_backend):
        cfg = default_config()
        cfg.scheduler.scale_down_threshold = 10
        qm = QueueManager("t", config=cfg, clock=fake_clock,
                          backend=queue_backend, enable_metrics=False)
        sig = qm.run_monitor_once()
        assert sig is not None and sig.direction == "down"

    def test_scale_signal_cooldown(self, fake_clock, queue_backend):
        """An idle manager must not spam 'down' signals every tick — only
        on edges (direction change) or after the cooldown."""
        signals = []
        cfg = default_config()
        cfg.scheduler.scale_down_threshold = 10
        cfg.scheduler.scale_up_threshold = 100
        cfg.scheduler.cooldown = 60.0
        qm = QueueManager("t", config=cfg, clock=fake_clock,
                          backend=queue_backend, enable_metrics=False,
                          scale_callback=signals.append)
        for _ in range(5):
            qm.run_monitor_once()
            fake_clock.advance(1.0)
        assert len(signals) == 1  # edge fired once, then suppressed
        fake_clock.advance(60.0)
        qm.run_monitor_once()
        assert len(signals) == 2  # cooldown elapsed → re-fired
        # First crossing in a new direction fires promptly (per-direction
        # cooldown), but a flap back to "down" within cooldown does not.
        for _ in range(100):
            qm.push_message(Message())
        qm.run_monitor_once()
        assert len(signals) == 3 and signals[-1].direction == "up"
        while qm.try_pop_message("normal"):
            pass
        qm.run_monitor_once()
        assert len(signals) == 3  # "down" still cooling — no spam on flap

    def test_stale_cleanup_real(self, fake_clock, queue_backend):
        # Real version of the reference's stub (queue_manager.go:549-553).
        cfg = default_config()
        cfg.queue.stale_message_age = 60.0
        cfg.scheduler.scale_down_threshold = -1  # no signal noise
        qm = QueueManager("t", config=cfg, clock=fake_clock,
                          backend=queue_backend, enable_metrics=False)
        stale = Message(content="stale")
        qm.push_message(stale)
        fake_clock.advance(120.0)
        fresh = Message(content="fresh")
        qm.push_message(fresh)
        qm.run_monitor_once()
        assert qm.queue.size("normal") == 1
        assert qm.pop_message("normal").content == "fresh"
