"""Ragged paged-attention plane (docs/performance.md "Ragged
attention"; PAPERS.md arxiv 2604.15464).

Three layers of pinning:

1. **Interpret-mode kernel equivalence** — the ragged Pallas kernel
   (bf16 + int8 variants) against the pure-JAX references in
   ops/attention.py: decode-only, prefill-only, mixed, GQA, int8
   scales, seq_len == 0 rows, slices crossing page boundaries. Runs
   the kernel BODY on CPU via ``interpret=True`` — no TPU needed.
2. **Engine-level token-for-token equivalence** — ragged on vs off
   through echo and CPU-JAX engines (pure fallback = the exact
   bucket-path ops), including prefix-cache continuation and the
   2-deep async pipeline.
3. **Surface collapse** — ragged warmup compiles strictly fewer
   programs (no per-bucket prefill), and the export-cache key includes
   the ragged geometry (a stale bucket-grid export must miss).

Compiled-path (real Mosaic lowering) cases are ``requires_tpu`` —
tier-1 auto-skips them on the CPU backend.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from llmq_tpu.core.config import (AsyncPipelineConfig, MixedBatchConfig,
                                  PrefixCacheConfig)
from llmq_tpu.core.types import Priority
from llmq_tpu.engine.engine import GenRequest, InferenceEngine
from llmq_tpu.engine.executor import EchoExecutor, JaxExecutor
from llmq_tpu.engine.tokenizer import ByteTokenizer
from llmq_tpu.models.llama import get_config, init_params
from llmq_tpu.ops.attention import (RAGGED_Q_BLOCK,
                                    _dequant_window, _gqa_attend,
                                    _scale_scatter,
                                    blockwise_prefill_attention,
                                    paged_decode_attention_pooled)
from llmq_tpu.ops.pallas.ragged_paged_attention import (
    ragged_kernel_viable, ragged_mixed_attention_pallas,
    ragged_mixed_attention_q8_pallas)
from llmq_tpu.ops.quant import quantize_kv_rows

QBLK = RAGGED_Q_BLOCK


# -- interpret-mode kernel harness ---------------------------------------------


class Geometry:
    """One mixed-batch geometry: decode rows with varied lengths and
    slices with (qstart, qlen) descriptors packed qblk-aligned. Builds
    pools, tables, packed buffers and the pure-JAX references."""

    def __init__(self, *, B, dec_lens, slices, H, Hkv, D, page_size,
                 max_pages, num_pages=64, n_layers=1, layer=0, seed=0,
                 int8=False):
        rng = np.random.RandomState(seed)
        self.B, self.H, self.Hkv, self.D = B, H, Hkv, D
        self.GD = Hkv * D
        self.ps, self.MP, self.layer = page_size, max_pages, layer
        self.int8 = int8
        L = n_layers
        if int8:
            self.k_pool = jnp.asarray(
                rng.randint(-127, 127, (L, num_pages, page_size, self.GD)),
                jnp.int8)
            self.v_pool = jnp.asarray(
                rng.randint(-127, 127, (L, num_pages, page_size, self.GD)),
                jnp.int8)
            self.ks_pool = jnp.asarray(
                rng.rand(L, num_pages, Hkv, page_size) * 0.1, jnp.bfloat16)
            self.vs_pool = jnp.asarray(
                rng.rand(L, num_pages, Hkv, page_size) * 0.1, jnp.bfloat16)
        else:
            self.k_pool = jnp.asarray(
                rng.randn(L, num_pages, page_size, self.GD),
                jnp.float32).astype(jnp.bfloat16)
            self.v_pool = jnp.asarray(
                rng.randn(L, num_pages, page_size, self.GD),
                jnp.float32).astype(jnp.bfloat16)
        self.dec_lens = np.asarray(dec_lens, np.int32)
        assert len(dec_lens) == B
        used = 1
        self.dec_bt = np.zeros((B, max_pages), np.int32)
        self.write_page = np.zeros(B, np.int32)
        for b in range(B):
            n = -(-max(1, int(self.dec_lens[b])) // page_size)
            for j in range(n):
                self.dec_bt[b, j] = used
                used += 1
            if self.dec_lens[b] > 0:
                self.write_page[b] = self.dec_bt[
                    b, (self.dec_lens[b] - 1) // page_size]
        self.S = len(slices)
        self.pf_qstart = np.asarray([s[0] for s in slices], np.int32)
        self.pf_qlen = np.asarray([s[1] for s in slices], np.int32)
        self.pf_qoff = np.zeros(self.S, np.int32)
        off = 0
        for i, (_st, ln) in enumerate(slices):
            self.pf_qoff[i] = off
            off += -(-ln // QBLK) * QBLK
        self.N = max(QBLK, off)
        self.pf_bt = np.zeros((self.S, max_pages), np.int32)
        for i in range(self.S):
            n = -(-int(self.pf_qstart[i] + self.pf_qlen[i]) // page_size)
            for j in range(n):
                self.pf_bt[i, j] = used
                used += 1
        assert used <= num_pages
        self.q_dec = jnp.asarray(rng.randn(B, H, D),
                                 jnp.float32).astype(jnp.bfloat16)
        self.k_new = jnp.asarray(rng.randn(B, Hkv, D),
                                 jnp.float32).astype(jnp.bfloat16)
        self.v_new = jnp.asarray(rng.randn(B, Hkv, D),
                                 jnp.float32).astype(jnp.bfloat16)
        self.q_pf = jnp.asarray(rng.randn(self.N, H, D),
                                jnp.float32).astype(jnp.bfloat16)
        self.bt_all = jnp.asarray(
            np.concatenate([self.dec_bt, self.pf_bt], 0))
        self.seq_all = jnp.asarray(np.concatenate(
            [self.dec_lens, self.pf_qstart + self.pf_qlen]))

    def run_kernel(self):
        if self.int8:
            kq, ks = quantize_kv_rows(self.k_new)
            vq, vs = quantize_kv_rows(self.v_new)
            self._kq, self._ks, self._vq, self._vs = kq, ks, vq, vs
            return ragged_mixed_attention_q8_pallas(
                self.q_dec, kq, ks, vq, vs, self.q_pf,
                (self.k_pool, self.v_pool, self.ks_pool, self.vs_pool),
                self.bt_all, self.seq_all, jnp.asarray(self.write_page),
                jnp.asarray(self.pf_qoff), jnp.asarray(self.pf_qlen),
                jnp.asarray(self.pf_qstart), self.layer, interpret=True)
        return ragged_mixed_attention_pallas(
            self.q_dec, self.k_new, self.v_new, self.q_pf,
            self.k_pool, self.v_pool, self.bt_all, self.seq_all,
            jnp.asarray(self.write_page), jnp.asarray(self.pf_qoff),
            jnp.asarray(self.pf_qlen), jnp.asarray(self.pf_qstart),
            self.layer, interpret=True)

    def ref_decode(self):
        """Scatter the current rows, then the pooled pure-JAX decode
        attention (rows with seq_len 0 produce garbage both places —
        masked out of the comparison by the caller)."""
        lens = np.maximum(self.dec_lens, 1)
        slot = (lens - 1) % self.ps
        if self.int8:
            kp = self.k_pool.at[self.layer, self.write_page, slot].set(
                self._kq.reshape(self.B, self.GD))
            vp = self.v_pool.at[self.layer, self.write_page, slot].set(
                self._vq.reshape(self.B, self.GD))
            ksp = _scale_scatter(self.ks_pool, self.layer,
                                 jnp.asarray(self.write_page),
                                 jnp.asarray(slot), self._ks)
            vsp = _scale_scatter(self.vs_pool, self.layer,
                                 jnp.asarray(self.write_page),
                                 jnp.asarray(slot), self._vs)
            k = _dequant_window(kp, ksp, self.layer,
                                jnp.asarray(self.dec_bt), self.D)
            v = _dequant_window(vp, vsp, self.layer,
                                jnp.asarray(self.dec_bt), self.D)
            return _gqa_attend(self.q_dec, k, v, jnp.asarray(self.dec_lens))
        kp = self.k_pool.at[self.layer, self.write_page, slot].set(
            self.k_new.reshape(self.B, self.GD))
        vp = self.v_pool.at[self.layer, self.write_page, slot].set(
            self.v_new.reshape(self.B, self.GD))
        return paged_decode_attention_pooled(
            self.q_dec, kp, vp, jnp.asarray(self.dec_bt),
            jnp.asarray(self.dec_lens), self.layer)

    def ref_slice(self, i):
        """Blockwise online-softmax reference for slice i's tokens."""
        T = int(self.pf_qlen[i])
        W = self.MP * self.ps
        qs = self.q_pf[int(self.pf_qoff[i]):int(self.pf_qoff[i]) + T][None]
        if self.int8:
            kh = _dequant_window(self.k_pool, self.ks_pool, self.layer,
                                 jnp.asarray(self.pf_bt[i][None]), self.D)
            vh = _dequant_window(self.v_pool, self.vs_pool, self.layer,
                                 jnp.asarray(self.pf_bt[i][None]), self.D)
        else:
            kh = self.k_pool[self.layer,
                             jnp.asarray(self.pf_bt[i])].reshape(
                                 1, W, self.Hkv, self.D)
            vh = self.v_pool[self.layer,
                             jnp.asarray(self.pf_bt[i])].reshape(
                                 1, W, self.Hkv, self.D)
        pos = jnp.asarray(self.pf_qstart[i] + np.arange(T))[None]
        sl = jnp.asarray([self.pf_qstart[i] + self.pf_qlen[i]])
        return blockwise_prefill_attention(qs, kh, vh, pos, sl)[0]

    def check(self, tol=0.15):
        attn_d, attn_p, pools = self.run_kernel()
        ref_d = self.ref_decode()
        live = self.dec_lens > 0
        err_d = np.abs(np.asarray(attn_d, np.float32)
                       - np.asarray(ref_d, np.float32))[live]
        assert err_d.size == 0 or err_d.max() < tol, err_d.max()
        for i in range(self.S):
            if self.pf_qlen[i] == 0:
                continue
            ref = self.ref_slice(i)
            got = attn_p[int(self.pf_qoff[i]):
                         int(self.pf_qoff[i]) + int(self.pf_qlen[i])]
            err = np.abs(np.asarray(got, np.float32)
                         - np.asarray(ref, np.float32))
            assert err.max() < tol, (i, err.max())
        return attn_d, attn_p, pools


class TestInterpretKernel:
    def test_mixed_decode_and_slices(self):
        g = Geometry(B=4, dec_lens=[1, 7, 13, 25], H=4, Hkv=2, D=64,
                     page_size=8, max_pages=4,
                     slices=[(5, 10), (0, 3)], seed=0)
        _, _, (k_out, _v) = g.check()
        # The kernel's fused writeback actually landed the new rows.
        slot = (g.dec_lens - 1) % g.ps
        wrote = np.asarray(k_out[g.layer, g.write_page, slot])
        want = np.asarray(g.k_new.reshape(g.B, g.GD), np.float32)
        assert np.abs(wrote.astype(np.float32) - want).max() == 0.0

    def test_decode_only_no_live_slices(self):
        # One dead padding slice (qlen 0 → owner-less blocks): a pure
        # decode batch through the ragged launch.
        g = Geometry(B=8, dec_lens=[1, 2, 3, 8, 9, 16, 17, 31],
                     H=4, Hkv=2, D=64, page_size=8, max_pages=4,
                     slices=[(0, 0)], seed=1)
        g.check()

    def test_prefill_only_frozen_decode_rows(self):
        # seq_len == 0 decode rows (frozen lanes writing to page 0).
        g = Geometry(B=4, dec_lens=[0, 0, 0, 0], H=4, Hkv=2, D=64,
                     page_size=8, max_pages=4,
                     slices=[(0, 12), (0, 7), (3, 5)], seed=2)
        _, attn_p, _ = g.check()
        assert np.all(np.isfinite(np.asarray(attn_p, np.float32)))

    def test_slice_crossing_page_boundary_with_history(self):
        # 20-token slice starting mid-page at absolute position 11:
        # spans three pages and attends to cached history.
        g = Geometry(B=4, dec_lens=[5, 1, 9, 2], H=8, Hkv=4, D=32,
                     page_size=8, max_pages=6,
                     slices=[(11, 20), (0, 1)], seed=3)
        g.check()

    def test_gqa_multiple_query_groups(self):
        g = Geometry(B=4, dec_lens=[3, 30, 12, 1], H=16, Hkv=2, D=64,
                     page_size=8, max_pages=4,
                     slices=[(2, 9)], seed=4)
        g.check()

    def test_nonzero_layer_of_stacked_pool(self):
        g = Geometry(B=4, dec_lens=[4, 6, 2, 10], H=4, Hkv=2, D=64,
                     page_size=8, max_pages=2, n_layers=3, layer=2,
                     slices=[(0, 5)], seed=5)
        g.check()

    def test_int8_scales_mixed(self):
        g = Geometry(B=2, dec_lens=[3, 140], H=16, Hkv=8, D=16,
                     page_size=128, max_pages=2,
                     slices=[(2, 9), (0, 4)], seed=6, int8=True)
        _, _, pools = g.check()
        # Scale writeback for the decode rows landed.
        slot = (g.dec_lens - 1) % g.ps
        wrote = np.asarray(pools[2][g.layer, g.write_page, :, slot],
                           np.float32)
        assert np.abs(wrote - np.asarray(g._ks, np.float32)).max() == 0.0

    def test_int8_long_slice_multiblock(self):
        g = Geometry(B=2, dec_lens=[1, 2], H=8, Hkv=8, D=16,
                     page_size=128, max_pages=2,
                     slices=[(0, 20), (5, 3)], seed=7, int8=True)
        g.check()

    def test_viability_gate(self):
        assert ragged_kernel_viable(4, 8, 4, 128, 4)
        assert not ragged_kernel_viable(4, 8, 4, 129, 4)   # lane align
        assert not ragged_kernel_viable(4, 6, 4, 128, 4)   # sublane ps
        # q_block × heads must stay sublane-aligned.
        assert not ragged_kernel_viable(4, 8, 4, 128, 3, q_block=1)


@pytest.mark.requires_tpu
class TestCompiledKernel:
    """Real-Mosaic lowering of the ragged kernel (the interpret suite
    covers semantics; this covers what interpret mode cannot — layout
    legality, DMA alignment, scoped-VMEM fit on chip)."""

    def test_compiled_matches_interpret(self):
        g = Geometry(B=8, dec_lens=[1, 7, 13, 25, 40, 2, 9, 33],
                     H=8, Hkv=4, D=32, page_size=8, max_pages=8,
                     slices=[(5, 10), (0, 3)], seed=0)
        attn_d_i, attn_p_i, _ = g.run_kernel()
        out = ragged_mixed_attention_pallas(
            g.q_dec, g.k_new, g.v_new, g.q_pf, g.k_pool, g.v_pool,
            g.bt_all, g.seq_all, jnp.asarray(g.write_page),
            jnp.asarray(g.pf_qoff), jnp.asarray(g.pf_qlen),
            jnp.asarray(g.pf_qstart), g.layer, interpret=False)
        assert np.abs(np.asarray(out[0], np.float32)
                      - np.asarray(attn_d_i, np.float32)).max() < 0.1
        assert np.abs(np.asarray(out[1], np.float32)
                      - np.asarray(attn_p_i, np.float32)).max() < 0.1


# -- engine-level token-for-token equivalence ----------------------------------


WAVE = [
    ("hello world this is a long prompt " * 3, Priority.NORMAL),
    ("short", Priority.REALTIME),
    ("medium sized prompt here", Priority.LOW),
    ("another quite long prompt for slicing " * 2, Priority.HIGH),
    ("fifth request", Priority.NORMAL),
    ("sixth one goes last", Priority.LOW),
]


def drive_wave(eng, wave=WAVE, conv=None, max_new=24):
    handles = []
    for i, (prompt, prio) in enumerate(wave):
        handles.append(eng.submit(GenRequest(
            id=f"r{i}", prompt=prompt, priority=prio,
            conversation_id=(conv[i] if conv else ""),
            max_new_tokens=max_new)))
        eng.step()
        eng.step()
    eng.run_until_idle()
    return handles


def make_echo_engine(ragged: bool, **kw):
    """Echo engines differ between ragged on/off only in the packing
    geometry the executor reports (capacity-wide slices vs fixed
    widths) — the stream contract must hold across that re-packing."""
    tok = ByteTokenizer()
    ex = EchoExecutor(batch_size=4, page_size=8, num_pages=256,
                      max_pages_per_seq=16, eos_id=tok.eos_id,
                      chunk_size=4, mixed_prefill_slices=2,
                      mixed_slice_tokens=(16 if ragged else 8), **kw)
    mixed = MixedBatchConfig(enabled=True, prefill_token_budget=16,
                             max_slices=2)
    return InferenceEngine(ex, tok, enable_metrics=False,
                           max_decode_steps=64, mixed_batch=mixed)


class TestEchoEquivalence:
    def test_token_budget_packing_streams_identical(self):
        def run(ragged):
            eng = make_echo_engine(ragged)
            handles = drive_wave(eng, max_new=40)
            return [h.result.tokens for h in handles]

        assert run(True) == run(False)

    def test_async_pipeline_two_deep(self):
        def run(ragged):
            tok = ByteTokenizer()
            ex = EchoExecutor(batch_size=4, page_size=8, num_pages=256,
                              max_pages_per_seq=16, eos_id=tok.eos_id,
                              chunk_size=4, mixed_prefill_slices=2,
                              mixed_slice_tokens=(16 if ragged else 8),
                              async_chunks=True)
            eng = InferenceEngine(
                ex, tok, enable_metrics=False, max_decode_steps=64,
                mixed_batch=MixedBatchConfig(enabled=True,
                                             prefill_token_budget=16,
                                             max_slices=2),
                async_pipeline=AsyncPipelineConfig(enabled=True, depth=2))
            handles = drive_wave(eng, max_new=32)
            eng.stop()
            return [h.result.tokens for h in handles]

        assert run(True) == run(False)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = get_config("llama3-tiny", max_seq_len=256, vocab_size=512)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def make_jax_engine(tiny_model, ragged: bool, *, slots=3,
                    prefix_cache=None, pipeline=None,
                    max_decode_steps=16):
    cfg, params = tiny_model
    tok = ByteTokenizer()
    ex = JaxExecutor(cfg, params, batch_size=slots, page_size=8,
                     num_pages=96, prefill_buckets=[16, 64],
                     eos_id=tok.eos_id, chunk_size=4,
                     mixed_prefill_slices=2, mixed_slice_tokens=8,
                     ragged_attention=ragged, ragged_token_capacity=16,
                     ragged_max_slices=2)
    return InferenceEngine(
        ex, tok, enable_metrics=False, max_decode_steps=max_decode_steps,
        prefix_cache=prefix_cache,
        mixed_batch=MixedBatchConfig(enabled=True,
                                     prefill_token_budget=16,
                                     max_slices=2),
        async_pipeline=pipeline)


class TestJaxEquivalence:
    """CPU-mode JAX (greedy): the ragged path runs the pure fallback —
    the exact bucket-path ops — so streams are token-for-token
    identical, while ALL prefill routes through the ragged program
    (no bucket programs exist on the ragged executor)."""

    def test_wave_with_preemption_streams_identical(self, tiny_model):
        def run(ragged):
            eng = make_jax_engine(tiny_model, ragged, slots=2)
            handles = []
            wave = [("a long prompt that needs slicing into chunks",
                     Priority.LOW),
                    ("second prompt arrives", Priority.NORMAL),
                    ("urgent!", Priority.REALTIME),
                    ("fourth one trails behind the others",
                     Priority.HIGH)]
            for i, (p, prio) in enumerate(wave):
                handles.append(eng.submit(GenRequest(
                    id=f"j{i}", prompt=p, priority=prio,
                    max_new_tokens=10)))
                eng.step()
                eng.step()
            eng.run_until_idle()
            return ([h.result.tokens for h in handles], eng)

        on, eng_on = run(True)
        off, _ = run(False)
        assert on == off
        assert eng_on.mixed_steps > 0, "ragged mixed path never ran"
        assert not any(p.startswith("prefill")
                       for p in eng_on.executor._aot)

    def test_prefix_cache_continuation_equivalence(self, tiny_model):
        def run(ragged):
            eng = make_jax_engine(
                tiny_model, ragged,
                prefix_cache=PrefixCacheConfig(enabled=True))
            out = []
            for turn in range(2):
                handles = []
                for c in range(3):
                    handles.append(eng.submit(GenRequest(
                        id=f"t{turn}c{c}",
                        prompt=f" turn {turn} for conversation {c}",
                        conversation_id=f"conv{c}",
                        max_new_tokens=8)))
                    eng.step()
                eng.run_until_idle()
                out.append([h.result.tokens for h in handles])
            assert eng.prefix_hits > 0 or any(
                h.result.cached_tokens > 0 for h in handles)
            return out

        assert run(True) == run(False)

    def test_async_pipeline_two_deep_equivalence(self, tiny_model):
        def run(ragged):
            eng = make_jax_engine(
                tiny_model, ragged,
                pipeline=AsyncPipelineConfig(enabled=True, depth=2))
            handles = drive_wave(eng, wave=WAVE[:4], max_new=8)
            eng.stop()
            return [h.result.tokens for h in handles]

        assert run(True) == run(False)

    def test_long_prompt_streams_through_capacity_chunks(self, tiny_model):
        """A prompt far beyond the packed capacity streams through
        repeated ragged dispatches (the executor re-chunks), then
        decodes to full length."""
        eng = make_jax_engine(tiny_model, True, max_decode_steps=12)
        h = eng.submit(GenRequest(id="long", prompt="x" * 150,
                                  max_new_tokens=12))
        eng.run_until_idle()
        assert h.result.finish_reason in ("eos", "length")
        assert h.result.prompt_tokens >= 150
        assert eng.allocator.used() == eng.allocator.pinned_pages()


# -- surface collapse + export-cache key ---------------------------------------


class TestSurfaceCollapse:
    def test_ragged_compiles_fewer_programs(self, tiny_model):
        cfg, params = tiny_model

        def warm(**kw):
            ex = JaxExecutor(cfg, params, batch_size=4, page_size=8,
                             num_pages=33, chunk_size=4,
                             prefill_buckets=[16, 32], eos_id=-1,
                             mixed_prefill_slices=2,
                             mixed_slice_tokens=8, **kw)
            ex.warmup()
            return ex

        bucket = warm(telemetry_name="rag-off")
        ragged = warm(telemetry_name="rag-on", ragged_attention=True,
                      ragged_token_capacity=16)
        assert len(ragged._aot) < len(bucket._aot)
        assert "ragged_chunk" in ragged._aot
        assert not any(p.startswith("prefill") for p in ragged._aot)

    def test_export_cache_key_includes_ragged_geometry(self, tiny_model):
        cfg, params = tiny_model

        def key(**kw):
            ex = JaxExecutor(cfg, params, batch_size=4, page_size=8,
                             num_pages=33, chunk_size=4,
                             prefill_buckets=[16, 32], eos_id=-1,
                             mixed_prefill_slices=2,
                             mixed_slice_tokens=8,
                             telemetry_name="rag-key", **kw)
            return ex._export_cache_key()

        k_bucket = key()
        k_ragged = key(ragged_attention=True, ragged_token_capacity=16)
        k_ragged2 = key(ragged_attention=True, ragged_token_capacity=32)
        assert k_bucket != k_ragged, "stale bucket-grid export would hit"
        assert k_ragged != k_ragged2, "capacity must be part of the key"
