"""Ring attention (sequence parallelism) vs dense reference, on the
virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmq_tpu.ops.attention import causal_prefill_attention
from llmq_tpu.ops.ring_attention import ring_attention_sharded
from llmq_tpu.parallel import make_mesh

B, T, H, HKV, D = 2, 64, 4, 2, 16


@pytest.fixture(scope="module")
def qkv():
    q = jax.random.normal(jax.random.PRNGKey(0), (B, T, H, D))
    k = jax.random.normal(jax.random.PRNGKey(1), (B, T, HKV, D))
    v = jax.random.normal(jax.random.PRNGKey(2), (B, T, HKV, D))
    return q, k, v


class TestRingAttention:
    def test_causal_matches_dense(self, qkv):
        q, k, v = qkv
        mesh = make_mesh({"sp": 8})
        out = ring_attention_sharded(mesh, q, k, v, causal=True)
        ref = causal_prefill_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_non_causal_matches_dense(self, qkv):
        q, k, v = qkv
        mesh = make_mesh({"sp": 8})
        out = ring_attention_sharded(mesh, q, k, v, causal=False)
        kk = jnp.repeat(k, H // HKV, axis=-2)
        vv = jnp.repeat(v, H // HKV, axis=-2)
        lg = jnp.einsum("bthd,bshd->bhts", q, kk) * (D ** -0.5)
        ref = jnp.einsum("bhts,bshd->bthd", jax.nn.softmax(lg, -1), vv)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_sp4_mesh(self, qkv):
        q, k, v = qkv
        mesh = make_mesh({"dp": 2, "sp": 4})
        out = ring_attention_sharded(mesh, q, k, v, causal=True)
        ref = causal_prefill_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


class TestLongContextPrefillSP:
    def test_full_model_sp_matches_dense_prefill(self):
        """forward_prefill_sp over sp=4 ≡ the dense paged prefill: the
        model-level long-context path is exact, not approximate."""
        from llmq_tpu.models.llama import (forward_prefill,
                                           forward_prefill_sp,
                                           get_config, init_kv_pages,
                                           init_params)

        cfg = get_config("llama3-tiny", max_seq_len=128, pallas=False,
                         dtype=jnp.float32)
        params = init_params(jax.random.PRNGKey(3), cfg)
        Bm, Tm = 2, 64
        tokens = jax.random.randint(jax.random.PRNGKey(4), (Bm, Tm), 5,
                                    cfg.vocab_size - 5, jnp.int32)

        mesh = make_mesh({"sp": 4}, devices=jax.devices()[:4])
        sp_logits = np.asarray(
            forward_prefill_sp(params, cfg, tokens, mesh))

        page = 16
        pages_per_seq = cfg.max_seq_len // page
        cache = init_kv_pages(cfg, Bm * pages_per_seq + 1, page)
        bt = np.zeros((Bm, pages_per_seq), np.int32)
        nxt = 1
        for b in range(Bm):
            for p in range(pages_per_seq):
                bt[b, p] = nxt
                nxt += 1
        positions = jnp.broadcast_to(jnp.arange(Tm), (Bm, Tm))
        lengths = jnp.full((Bm,), Tm, jnp.int32)
        dense_logits, _ = forward_prefill(
            params, cfg, tokens, positions, lengths, cache,
            jnp.asarray(bt))
        np.testing.assert_allclose(sp_logits, np.asarray(dense_logits),
                                   rtol=2e-4, atol=2e-4)
