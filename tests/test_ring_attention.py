"""Ring attention (sequence parallelism) vs dense reference, on the
virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmq_tpu.ops.attention import causal_prefill_attention
from llmq_tpu.ops.ring_attention import ring_attention_sharded
from llmq_tpu.parallel import make_mesh

B, T, H, HKV, D = 2, 64, 4, 2, 16


@pytest.fixture(scope="module")
def qkv():
    q = jax.random.normal(jax.random.PRNGKey(0), (B, T, H, D))
    k = jax.random.normal(jax.random.PRNGKey(1), (B, T, HKV, D))
    v = jax.random.normal(jax.random.PRNGKey(2), (B, T, HKV, D))
    return q, k, v


class TestRingAttention:
    def test_causal_matches_dense(self, qkv):
        q, k, v = qkv
        mesh = make_mesh({"sp": 8})
        out = ring_attention_sharded(mesh, q, k, v, causal=True)
        ref = causal_prefill_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_non_causal_matches_dense(self, qkv):
        q, k, v = qkv
        mesh = make_mesh({"sp": 8})
        out = ring_attention_sharded(mesh, q, k, v, causal=False)
        kk = jnp.repeat(k, H // HKV, axis=-2)
        vv = jnp.repeat(v, H // HKV, axis=-2)
        lg = jnp.einsum("bthd,bshd->bhts", q, kk) * (D ** -0.5)
        ref = jnp.einsum("bhts,bshd->bthd", jax.nn.softmax(lg, -1), vv)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_sp4_mesh(self, qkv):
        q, k, v = qkv
        mesh = make_mesh({"dp": 2, "sp": 4})
        out = ring_attention_sharded(mesh, q, k, v, causal=True)
        ref = causal_prefill_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
