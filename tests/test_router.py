"""Multi-engine serving through the LoadBalancer (VERDICT r3 #8).

End-to-end on the message path the reference never wires (SURVEY §3.5):
QueueManager → Worker → EngineRouter.process_fn → LoadBalancer
get_endpoint → engine.process_fn → release_endpoint. Covers conversation
affinity across replicas, per-endpoint load feedback, and failover when
an engine dies (health state machine → UNHEALTHY → traffic moves).
"""


import pytest

from llmq_tpu.core.config import LoadBalancerConfig
from llmq_tpu.core.types import Message, MessageStatus
from llmq_tpu.engine.engine import InferenceEngine
from llmq_tpu.engine.executor import EchoExecutor
from llmq_tpu.engine.tokenizer import ByteTokenizer
from llmq_tpu.loadbalancer import EndpointStatus, EngineRouter, LoadBalancer
from llmq_tpu.queueing.queue_manager import QueueManager
from llmq_tpu.queueing.worker import Worker


def make_engine(name: str) -> InferenceEngine:
    tok = ByteTokenizer()
    ex = EchoExecutor(batch_size=4, page_size=8, num_pages=128,
                      max_pages_per_seq=16, eos_id=tok.eos_id)
    eng = InferenceEngine(ex, tok, name=name, enable_metrics=False,
                          max_decode_steps=32)
    eng.start()
    return eng


@pytest.fixture
def duo():
    """Two live echo engines behind one LoadBalancer + router."""
    lb = LoadBalancer(LoadBalancerConfig(strategy="round_robin",
                                         health_check_interval=0))
    router = EngineRouter(lb)
    engines = [make_engine("e0"), make_engine("e1")]
    for e in engines:
        router.register_engine(e)
    yield lb, router, engines
    for e in engines:
        e.stop()


class TestEngineRouter:
    def test_messages_route_across_engines(self, duo):
        lb, router, engines = duo
        qm = QueueManager("routed", enable_metrics=False)
        w = Worker("w0", qm, router.process_fn)
        msgs = [Message(id=f"m{i}", content=f"hello {i}", timeout=30.0)
                for i in range(6)]
        for m in msgs:
            qm.push_message(m)
        w.process_batch()
        assert all(m.status == MessageStatus.COMPLETED for m in msgs)
        assert all(m.response for m in msgs)
        # Round-robin spread both engines.
        used = {m.metadata["endpoint_id"] for m in msgs}
        assert used == {"e0", "e1"}
        stats = {ep.id: ep.total_requests for ep in lb.endpoints()}
        assert stats["e0"] == 3 and stats["e1"] == 3
        # Response-time EWMA fed back on release.
        assert all(ep.response_time > 0 for ep in lb.endpoints())

    def test_conversation_affinity_pins_replica(self, duo):
        lb, router, engines = duo
        qm = QueueManager("conv", enable_metrics=False)
        w = Worker("w0", qm, router.process_fn)
        # Interleave two conversations; every turn of a conversation
        # must land on the engine holding its KV.
        msgs = []
        for turn in range(3):
            for conv in ("ca", "cb"):
                m = Message(id=f"{conv}-{turn}", content=f"turn {turn}",
                            conversation_id=conv, timeout=30.0)
                msgs.append(m)
                qm.push_message(m)
                w.process_batch()
        by_conv = {}
        for m in msgs:
            by_conv.setdefault(m.conversation_id, set()).add(
                m.metadata["endpoint_id"])
        assert all(len(eps) == 1 for eps in by_conv.values()), by_conv
        # The pinned engine actually reused the conversation KV.
        for conv, (eid,) in ((c, tuple(e)) for c, e in by_conv.items()):
            eng = next(e for e in engines if e.name == eid)
            assert conv in eng.cached_conversations()

    def test_dead_engine_fails_over(self, duo):
        lb, router, engines = duo
        e0, e1 = engines
        e0.stop()                      # killed replica
        # Health state machine: consecutive failures → UNHEALTHY.
        for _ in range(5):
            lb.check_health_once()
        assert lb.get_endpoint_by_id("e0").status == EndpointStatus.UNHEALTHY
        assert lb.get_endpoint_by_id("e1").status == EndpointStatus.HEALTHY

        qm = QueueManager("failover", enable_metrics=False)
        w = Worker("w0", qm, router.process_fn)
        msgs = [Message(id=f"f{i}", content="x", timeout=30.0)
                for i in range(4)]
        for m in msgs:
            qm.push_message(m)
        w.process_batch()
        assert all(m.status == MessageStatus.COMPLETED for m in msgs)
        assert {m.metadata["endpoint_id"] for m in msgs} == {"e1"}

        # Recovery: restart e0, probes pass, traffic returns (through
        # DEGRADED first, per the state machine).
        e0.start()
        for _ in range(6):
            lb.check_health_once()
        assert lb.get_endpoint_by_id("e0").status in (
            EndpointStatus.HEALTHY, EndpointStatus.DEGRADED)
        more = [Message(id=f"r{i}", content="x", timeout=30.0)
                for i in range(4)]
        for m in more:
            qm.push_message(m)
        w.process_batch()
        assert {m.metadata["endpoint_id"] for m in more} == {"e0", "e1"}

    def test_affinity_failover_rebuilds_conversation(self, duo):
        """A conversation pinned to a replica that dies continues on the
        surviving one via the history_text fallback path."""
        lb, router, engines = duo
        e0, e1 = engines
        qm = QueueManager("cf", enable_metrics=False)
        w = Worker("w0", qm, router.process_fn)
        m1 = Message(id="t1", content="first turn", conversation_id="cx",
                     timeout=30.0)
        qm.push_message(m1)
        w.process_batch()
        first_ep = m1.metadata["endpoint_id"]
        dead = next(e for e in engines if e.name == first_ep)
        alive = next(e for e in engines if e.name != first_ep)
        dead.stop()
        for _ in range(5):
            lb.check_health_once()
        m2 = Message(id="t2", content="second turn", conversation_id="cx",
                     timeout=30.0,
                     metadata={"history_text": m1.content + m1.response})
        qm.push_message(m2)
        w.process_batch()
        assert m2.status == MessageStatus.COMPLETED
        assert m2.metadata["endpoint_id"] == alive.name


class TestRouterErrors:
    def test_engine_error_feeds_error_rate(self):
        lb = LoadBalancer(LoadBalancerConfig(strategy="round_robin",
                                             health_check_interval=0))
        router = EngineRouter(lb)
        eng = make_engine("solo")
        router.register_engine(eng)
        qm = QueueManager("err", enable_metrics=False)
        qm.config.queue.retry.max_retries = 0

        def broken(ctx, msg):
            raise RuntimeError("endpoint exploded")

        eng.process_fn = broken
        w = Worker("w0", qm, router.process_fn)
        m = Message(id="boom", content="x", timeout=5.0, max_retries=0)
        qm.push_message(m)
        w.process_batch()
        assert m.status in (MessageStatus.FAILED, MessageStatus.TIMEOUT)
        ep = lb.get_endpoint_by_id("solo")
        assert ep.total_errors == 1 and ep.error_rate > 0
        eng.stop()
