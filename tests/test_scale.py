"""Scale sanity: 8B/70B sizing math and the HF checkpoint import path.

The big configs are never materialized in CI (70B is ~141 GB of bf16);
these tests pin down the *arithmetic* the scheduler and deployment docs
rely on — param counts of the public Llama-3 architectures, HBM-fit
against the topology table — and exercise ``import_hf_llama`` end-to-end
on a synthetic 2-layer safetensors checkpoint.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from llmq_tpu.models.llama import (  # noqa: E402
    forward_prefill,
    get_config,
    init_kv_pages,
    init_params,
    kv_bytes_per_token,
    param_count,
    param_count_analytic,
    weight_bytes,
)
from llmq_tpu.scheduling.topology import TpuTopology  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestParamCounts:
    def test_analytic_matches_materialized(self):
        for name in ("llama3-tiny",):
            cfg = get_config(name)
            params = init_params(jax.random.PRNGKey(0), cfg)
            assert param_count(params) == param_count_analytic(cfg)

    def test_llama3_1b(self):
        # Public Llama-3.2-1B: 1.24B parameters (tied embeddings).
        n = param_count_analytic(get_config("llama3-1b"))
        assert abs(n - 1.236e9) / 1.236e9 < 0.01, n

    def test_llama3_8b(self):
        # Public Llama-3-8B: 8.03B parameters.
        n = param_count_analytic(get_config("llama3-8b"))
        assert abs(n - 8.03e9) / 8.03e9 < 0.01, n

    def test_llama3_70b(self):
        # Public Llama-3-70B: 70.6B parameters.
        n = param_count_analytic(get_config("llama3-70b"))
        assert abs(n - 70.6e9) / 70.6e9 < 0.01, n


class TestHbmFit:
    """BASELINE sizing claims, checked against topology.py's HBM table."""

    def _fits(self, cfg, topo, *, kv_tokens: int = 0,
              overhead_frac: float = 0.1) -> bool:
        need = weight_bytes(cfg) + kv_tokens * kv_bytes_per_token(cfg)
        budget = topo.total_hbm_gb * (1 - overhead_frac) * 1e9
        return need <= budget

    def test_1b_fits_single_v5e(self):
        # The single-chip bench model: 1B bf16 (2.5 GB) + a 4096-token
        # KV pool on one 16 GB v5e chip.
        cfg = get_config("llama3-1b")
        topo = TpuTopology.declare(1, kind="v5e")
        assert self._fits(cfg, topo, kv_tokens=64 * 4096)

    def test_8b_needs_multichip(self):
        # 8B bf16 is ~16.06 GB — does NOT fit one 16 GB v5e chip; fits
        # v5e-8 with a large KV pool (BASELINE config #2 on v5e-8).
        cfg = get_config("llama3-8b")
        one = TpuTopology.declare(1, kind="v5e")
        eight = TpuTopology.declare(8, kind="v5e")
        assert not self._fits(cfg, one)
        # 64 concurrent 8k sequences: 64·8192 tokens × 128 KiB = 68 GB.
        assert self._fits(cfg, eight, kv_tokens=64 * 8192)

    def test_70b_needs_v5e16(self):
        # 70B bf16 is ~141 GB — exceeds v5e-8 (128 GB), fits 2-host
        # v5e-16 (256 GB) with KV headroom: BASELINE config #5.
        cfg = get_config("llama3-70b")
        eight = TpuTopology.declare(8, kind="v5e")
        sixteen = TpuTopology.declare(16, num_hosts=2, kind="v5e")
        assert not self._fits(cfg, eight)
        # 24 concurrent 8k sequences: 24·8192 tokens × 320 KiB = 63 GB.
        assert self._fits(cfg, sixteen, kv_tokens=24 * 8192)

    def test_kv_bytes_per_token(self):
        # 8B: 2 × 32 layers × 8 kv-heads × 128 dim × 2 B = 131072 B/token.
        assert kv_bytes_per_token(get_config("llama3-8b")) == 131072


_AOT_70B = r"""
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 16)
except AttributeError:
    pass
import jax.numpy as jnp
from llmq_tpu.models.llama import (forward_decode, get_config,
                                   init_kv_pages, init_params_quantized)
from llmq_tpu.parallel.mesh import make_mesh
from llmq_tpu.parallel.sharding import (batch_sharding,
                                        kv_cache_shardings,
                                        param_shardings)

assert len(jax.devices()) == 16, len(jax.devices())
# The flagship serving config (BASELINE #5): llama3-70b int8 on a
# 2-host v5e-16, dp x tp = 2 x 8 — tp=8 so the 8 GQA KV heads still
# shard (tp=16 would force full KV replication per chip).
cfg = get_config("llama3-70b", max_seq_len=8192)
mesh = make_mesh({{"dp": 2, "tp": 8}})
B, page_size = 8, 128
mpps = cfg.max_seq_len // page_size
num_pages = B * mpps + 1

# ABSTRACT params/cache: eval_shape traces the initializers without a
# byte of HBM — 70B int8 is ~70 GB that CI never materializes.
abs_params = jax.eval_shape(
    lambda: init_params_quantized(jax.random.PRNGKey(0), cfg))
abs_cache = jax.eval_shape(lambda: init_kv_pages(cfg, num_pages,
                                                 page_size))

def with_sharding(avals, shardings):
    return jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        avals, shardings)

a_params = with_sharding(abs_params,
                         param_shardings(cfg, mesh, quantized=True))
a_cache = with_sharding(dict(abs_cache), dict(kv_cache_shardings(cfg, mesh)))
a_tok = jax.ShapeDtypeStruct((B,), jnp.int32,
                             sharding=batch_sharding(mesh, 1))
a_pos = jax.ShapeDtypeStruct((B,), jnp.int32,
                             sharding=batch_sharding(mesh, 1))
a_bt = jax.ShapeDtypeStruct((B, mpps), jnp.int32,
                            sharding=batch_sharding(mesh, 2))

f = jax.jit(lambda p, t, pos, c, bt: forward_decode(p, cfg, t, pos, c, bt))
compiled = f.lower(a_params, a_tok, a_pos, a_cache, a_bt).compile()

# Record that the flagship sharding FITS a v5e chip: per-device
# argument bytes (weights shard over tp; cache over tp KV heads) under
# the 16 GB HBM with scheduler headroom.
mem = compiled.memory_analysis()
per_dev_gb = mem.argument_size_in_bytes / 1e9
assert per_dev_gb < 16.0 * 0.9, f"{{per_dev_gb:.1f}} GB/chip"
print(f"AOT70B OK {{per_dev_gb:.2f}} GB/chip", flush=True)
"""


@pytest.mark.skipif(os.environ.get("LLMQ_SKIP_MULTIPROC") == "1",
                    reason="multi-process test disabled")
def test_70b_dp2tp8_aot_lowering_compiles():
    """Flagship multi-chip validity without HBM: the REAL llama3-70b
    int8 config AOT-lowers and compiles at dp*tp=16 from
    ShapeDtypeStructs on a 16-virtual-device CPU mesh, and the
    per-device argument footprint fits a 16 GB v5e chip. Subprocess:
    the test session's JAX is pinned to 8 devices (conftest)."""
    script = _AOT_70B.format(repo=REPO)
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))
           and k not in ("PYTHONPATH", "PYTHONSTARTUP")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    p = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "AOT70B OK" in p.stdout, p.stdout


class TestHfImport:
    """import_hf_llama on a synthetic 2-layer safetensors checkpoint."""

    @pytest.fixture
    def hf_dir(self, tmp_path):
        st = pytest.importorskip("safetensors.numpy")
        cfg = get_config("llama3-tiny")
        rng = np.random.default_rng(0)

        def w(o, i):
            return (rng.standard_normal((o, i)) * 0.02).astype(np.float32)

        tensors = {"model.embed_tokens.weight": w(cfg.vocab_size, cfg.dim),
                   "model.norm.weight": np.ones(cfg.dim, np.float32),
                   "lm_head.weight": w(cfg.vocab_size, cfg.dim)}
        hd = cfg.head_dim
        for i in range(cfg.n_layers):
            p = f"model.layers.{i}"
            tensors[f"{p}.self_attn.q_proj.weight"] = w(
                cfg.n_heads * hd, cfg.dim)
            tensors[f"{p}.self_attn.k_proj.weight"] = w(
                cfg.n_kv_heads * hd, cfg.dim)
            tensors[f"{p}.self_attn.v_proj.weight"] = w(
                cfg.n_kv_heads * hd, cfg.dim)
            tensors[f"{p}.self_attn.o_proj.weight"] = w(
                cfg.dim, cfg.n_heads * hd)
            tensors[f"{p}.mlp.gate_proj.weight"] = w(cfg.ffn_dim, cfg.dim)
            tensors[f"{p}.mlp.up_proj.weight"] = w(cfg.ffn_dim, cfg.dim)
            tensors[f"{p}.mlp.down_proj.weight"] = w(cfg.dim, cfg.ffn_dim)
            tensors[f"{p}.input_layernorm.weight"] = np.ones(
                cfg.dim, np.float32)
            tensors[f"{p}.post_attention_layernorm.weight"] = np.ones(
                cfg.dim, np.float32)
        st.save_file(tensors, str(tmp_path / "model.safetensors"))
        return tmp_path, cfg, tensors

    def test_import_shapes_and_values(self, hf_dir):
        from llmq_tpu.models.checkpoint import import_hf_llama
        tmp_path, cfg, tensors = hf_dir
        params = import_hf_llama(str(tmp_path), cfg)
        assert param_count(params) == param_count_analytic(cfg)
        # HF stores (out, in); ours is (in, out): verbatim transpose —
        # NO rope permutation for HF safetensors (ADVICE r1 high).
        want = tensors["model.layers.0.self_attn.q_proj.weight"].T
        got = np.asarray(params["layers"]["wq"][0], np.float32)
        np.testing.assert_allclose(got, want.astype(np.float32), atol=2e-2)

    def test_imported_model_runs(self, hf_dir):
        from llmq_tpu.models.checkpoint import import_hf_llama
        tmp_path, cfg, _ = hf_dir
        params = import_hf_llama(str(tmp_path), cfg)
        cache = init_kv_pages(cfg, 8, 8)
        B, T = 1, 4
        toks = jnp.asarray([[5, 9, 2, 7]], jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(T), (B, T)).astype(jnp.int32)
        lens = jnp.full((B,), T, jnp.int32)
        bt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        logits, _ = forward_prefill(params, cfg, toks, pos, lens, cache, bt)
        assert logits.shape == (B, T, cfg.vocab_size)
        assert np.isfinite(np.asarray(logits)).all()

    def test_meta_rope_permutation(self, hf_dir):
        """meta_rope_layout=True applies the interleaved→split-half
        permutation; verify it is exactly HF's conversion permutation."""
        from llmq_tpu.models.checkpoint import _permute_meta_rope
        _, cfg, _ = hf_dir
        hd = cfg.head_dim
        n = cfg.n_heads
        # Build a marker matrix: row index encodes (head, dim_pos).
        w = np.arange(n * hd, dtype=np.float32)[:, None] * np.ones(
            (1, cfg.dim), np.float32)
        out = _permute_meta_rope(w, n)
        # Meta interleaved row order per head: [0,2,4,...,1,3,5,...]
        for h in range(n):
            rows = out[h * hd:(h + 1) * hd, 0] - h * hd
            expect = np.concatenate([np.arange(0, hd, 2),
                                     np.arange(1, hd, 2)])
            np.testing.assert_array_equal(rows, expect)
