"""Scenario engine (llmq_tpu/scenarios/, docs/scenarios.md): spec
model + compiler determinism, the closed-loop driver against the real
engine path (multi-turn re-arrival, quota shedding, chaos kills with
supervisor recovery), and the scorer's report contract — goodput,
share error, waste, tier hits, invariants, SCENARIO_<name>.json.

The reduced-scale runs here are the CI smoke for the shipped
scenarios; the full-scale ``conversation_soak_100k`` acceptance bar
(goodput within 10% of steady state through one diurnal cycle + two
kills) is the ``slow``-marked test at the bottom.
"""

from __future__ import annotations

import json
import logging
import os

import pytest

from llmq_tpu import chaos
from llmq_tpu.core.config import ChaosConfig
from llmq_tpu.observability.usage import get_usage_ledger
from llmq_tpu.scenarios import (SHIPPED, compile_scenario, list_scenarios,
                                load_named, run_scenario, spec_from_dict,
                                steady_state_deviation)
from llmq_tpu.tenancy import get_tenant_registry, reset_tenancy

pytestmark = [
    pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning"),
]

#: Loggers that narrate every preemption/eviction/crash during a run —
#: megabytes of INFO on a 10^4-request scenario; errors still surface.
_NOISY = ("llmq.engine", "llmq.supervisor", "llmq.chaos",
          "llmq.tiering", "llmq.scenarios")


@pytest.fixture(autouse=True)
def _quiet_and_reset():
    prev = {}
    for name in _NOISY:
        lg = logging.getLogger(name)
        prev[name] = lg.level
        lg.setLevel(logging.ERROR)
    yield
    for name, lvl in prev.items():
        logging.getLogger(name).setLevel(lvl)
    chaos.configure(ChaosConfig(enabled=False))
    reset_tenancy()
    led = get_usage_ledger()
    led.reconfigure(enabled=False)
    led.clear()
    from llmq_tpu.observability.recorder import get_recorder
    get_recorder().clear()


# -- spec + compiler -----------------------------------------------------------


class TestSpecAndCompiler:
    def test_shipped_scenarios_all_load(self):
        names = list_scenarios()
        for want in SHIPPED:
            assert want in names
        for name in SHIPPED:
            spec = load_named(name)
            assert spec.name == name
            assert spec.phases and spec.populations

    def test_compile_is_deterministic(self):
        """Acceptance bar: same spec + seed ⇒ identical schedule."""
        for name in SHIPPED:
            spec = load_named(name)
            a = compile_scenario(spec, scale=0.02)
            b = compile_scenario(spec, scale=0.02)
            assert a.schedule_digest() == b.schedule_digest(), name
            assert [x.t for x in a.arrivals] == [x.t for x in b.arrivals]

    def test_seed_changes_schedule(self):
        spec = load_named("agentic_tool_loops")
        base = compile_scenario(spec, scale=0.1).schedule_digest()
        spec.seed += 1
        assert compile_scenario(spec, scale=0.1).schedule_digest() != base

    def test_scale_thins_arrivals(self):
        spec = load_named("conversation_soak_100k")
        small = compile_scenario(spec, scale=0.01)
        big = compile_scenario(spec, scale=0.03)
        assert 0 < len(small.arrivals) < len(big.arrivals)
        cap = int(spec.max_conversations * 0.01)
        assert len(small.arrivals) <= cap

    def test_unknown_key_rejected(self):
        with pytest.raises(ValueError, match="unknown"):
            spec_from_dict({"name": "x", "bogus": 1})

    def test_bad_arrival_kind_rejected(self):
        with pytest.raises(ValueError, match="arrival kind"):
            spec_from_dict({
                "name": "x",
                "phases": [{"name": "p", "duration_s": 1.0,
                            "arrival": {"kind": "zipf"}}],
                "populations": [{"name": "p0"}],
            })

    def test_replay_arrivals_from_trace(self, tmp_path):
        trace = tmp_path / "trace.jsonl"
        trace.write_text('{"at": 0.25}\n0.75\n{"at": 99.0}\n2.5\n')
        spec = spec_from_dict({
            "name": "rp", "seed": 7,
            "phases": [{"name": "p", "duration_s": 3.0,
                        "arrival": {"kind": "replay",
                                    "trace_file": str(trace)}}],
            "populations": [{"name": "p0", "turns_min": 1,
                             "turns_max": 1}],
        })
        compiled = compile_scenario(spec)
        # 99.0 falls outside the phase; the rest replay in order.
        assert [a.t for a in compiled.arrivals] == [0.25, 0.75, 2.5]

    def test_replay_requires_trace_file(self):
        with pytest.raises(ValueError, match="trace_file"):
            spec_from_dict({
                "name": "x",
                "phases": [{"name": "p", "duration_s": 1.0,
                            "arrival": {"kind": "replay"}}],
                "populations": [{"name": "p0"}],
            })


# -- reduced-scale closed-loop runs (CI smoke) ---------------------------------


class TestScenarioRuns:
    def test_agentic_loop_report_contract(self, tmp_path):
        """Acceptance bar: each run emits SCENARIO_<name>.json with
        goodput, share-error, waste and tier-hit fields populated."""
        rep = run_scenario("agentic_tool_loops", scale=0.05,
                           out_dir=str(tmp_path), emit_json=True)
        req = rep["requests"]
        assert req["submitted"] > 0
        assert req["completed"] == req["turns_planned"]
        assert req["failed"] == 0 and req["shed"] == 0
        # Goodput joined from the usage ledger, not driver arithmetic.
        assert rep["goodput"]["tokens_per_device_second"] > 0
        assert rep["driver_goodput_tps"] > 0
        assert rep["slo"]["met_requests"] > 0
        # Multi-turn share mix lands close to the compiled plan.
        se = rep["share_error"]
        assert set(se["tenants"]) == {"agents-team", "batch-agents"}
        assert se["max_abs_error"] < 0.2
        assert "by_reason" in rep["waste"] and "ratio" in rep["waste"]
        assert rep["tier_hits"]["requests_by_tier"]
        inv = rep["invariants"]
        assert inv["violations"] == 0
        assert inv["submitted"] == req["submitted"]
        assert inv["terminal"]["completed"] == req["completed"]
        # FakeClock compression: 30 virtual seconds in far less wall.
        assert rep["duration"]["compression"] > 1.0
        path = os.path.join(str(tmp_path),
                            "SCENARIO_agentic_tool_loops.json")
        assert rep["report_path"] == path
        with open(path, "r", encoding="utf-8") as f:
            on_disk = json.load(f)
        for key in ("goodput", "share_error", "waste", "tier_hits",
                    "invariants", "timeline", "schedule_digest"):
            assert key in on_disk, key

    def test_run_is_deterministic(self):
        a = run_scenario("rag_long_prompt_flood", scale=0.1)
        b = run_scenario("rag_long_prompt_flood", scale=0.1)
        assert a["schedule_digest"] == b["schedule_digest"]
        assert a["requests"]["turns_planned"] == \
            b["requests"]["turns_planned"]
        assert a["tokens"] == b["tokens"]

    def test_flash_crowd_survives_chaos_kill(self):
        """The diurnal+flash-crowd scenario arms a mid-run engine
        crash; the supervisor recovers and the driver retries — zero
        loss, no duplicate terminal states."""
        rep = run_scenario("diurnal_tenant_mix_with_flash_crowd",
                           scale=0.1)
        req = rep["requests"]
        assert req["chaos_events_fired"] == 1
        assert req["engine_recoveries"] >= 1
        assert req["completed"] == req["turns_planned"]
        assert req["submitted"] == req["completed"] + req["failed"]
        assert req["retried"] == req["failed"]
        assert rep["invariants"]["violations"] == 0
        assert rep["goodput"]["tokens_per_device_second"] > 0
        assert set(rep["share_error"]["tenants"]) == \
            {"gold", "silver", "bronze"}

    def test_spray_probe_sheds_at_quota_edge(self):
        """Sprayed fresh tenant ids get their first turn admitted
        (burst debt) and their second shed by the rate quota; the
        configured tenant keeps flowing; the rejection counter drains
        through the tenancy flush."""
        rep = run_scenario("adversarial_id_spray_quota_probe",
                           scale=0.15)
        req = rep["requests"]
        assert req["shed"] > 0
        assert req["completed"] > 0
        assert rep["tenancy"]["rejections"].get("rate", 0) == req["shed"]
        # The legit configured tenant is never quota-shed.
        assert "acme" in rep["share_error"]["tenants"]
        assert rep["share_error"]["tenants"]["acme"]["achieved_share"] > 0
        from llmq_tpu.metrics.registry import exposition
        text = exposition().decode()
        assert "llm_queue_tenant_registry_evictions_total" in text

    def test_soak_ci_smoke(self):
        """Reduced-scale conversation soak: both chaos kills fire and
        recover, the run is zero-loss, goodput is populated. The 10%
        steady-state bar is pinned at full scale by the slow test —
        at this scale per-tick batches are too small for stable
        batching economics."""
        rep = run_scenario("conversation_soak_100k", scale=0.02)
        req = rep["requests"]
        assert req["chaos_events_fired"] == 2
        assert req["engine_recoveries"] == 2
        assert req["completed"] == req["turns_planned"]
        assert req["submitted"] == req["completed"] + req["failed"]
        assert rep["invariants"]["violations"] == 0
        assert rep["goodput"]["tokens_per_device_second"] > 0
        assert len(rep["timeline"]) >= 6
        assert steady_state_deviation(rep) is not None


# -- tenant-registry eviction counter (ISSUE satellite) ------------------------


def _evictions_sample() -> float:
    """Read the eviction counter's exposition sample value."""
    from llmq_tpu.metrics.registry import REGISTRY
    for fam in REGISTRY.collect():
        if fam.name == "llm_queue_tenant_registry_evictions":
            for s in fam.samples:
                if s.name.endswith("_total"):
                    return float(s.value)
    return 0.0


class TestRegistryEvictionCounter:
    def test_lru_bound_evictions_counted_and_drained(self):
        from llmq_tpu.core.config import (TenancyConfig,
                                          TenantClassConfig)
        from llmq_tpu.tenancy import configure_tenancy
        # A finite default rate so every sprayed id mints bucket state.
        reg = configure_tenancy(TenancyConfig(
            enabled=True,
            default=TenantClassConfig(token_rate=1000.0,
                                      burst_tokens=2000.0)))
        spray = reg.MAX_TRACKED + 500
        for i in range(spray):
            reg.admit_tokens(f"ev-spray-{i}", 4.0)
        assert reg.evictions_total == 500
        drained = reg.drain_evictions()
        assert drained == reg.evictions_total
        assert reg.drain_evictions() == 0      # drain is destructive
        # clear() resets both the total and any pending drain.
        reg.admit_tokens("ev-one-more", 4.0)
        reg.clear()
        assert reg.evictions_total == 0 and reg.drain_evictions() == 0

    def test_counter_flushes_into_exposition(self):
        from llmq_tpu.core.config import (TenancyConfig,
                                          TenantClassConfig)
        from llmq_tpu.metrics.registry import exposition
        from llmq_tpu.tenancy import configure_tenancy
        reg = configure_tenancy(TenancyConfig(
            enabled=True,
            default=TenantClassConfig(token_rate=1000.0,
                                      burst_tokens=2000.0)))
        exposition()                   # settle any pending drains
        before = _evictions_sample()
        for i in range(reg.MAX_TRACKED + 100):
            reg.admit_tokens(f"fl-spray-{i}", 4.0)
        text = exposition().decode()   # scrape drives the flush chain
        assert "llm_queue_tenant_registry_evictions_total" in text
        assert _evictions_sample() - before == 100


# -- full-scale acceptance bars ------------------------------------------------


@pytest.mark.slow
class TestFullScaleSoak:
    def test_conversation_soak_100k_holds_goodput(self):
        """THE acceptance bar: ~10^5 conversations on the echo
        backend, FakeClock-compressed, goodput within 10% of steady
        state through one full diurnal cycle and two chaos kills, with
        zero-loss / zero-dup / monotone-stream invariants."""
        rep = run_scenario("conversation_soak_100k", scale=1.0)
        req = rep["requests"]
        assert req["conversations"] > 80_000
        assert req["chaos_events_fired"] == 2
        assert req["engine_recoveries"] == 2
        assert req["completed"] == req["turns_planned"]
        assert req["submitted"] == req["completed"] + req["failed"]
        assert req["retried"] == req["failed"]
        assert rep["invariants"]["violations"] == 0
        assert rep["goodput"]["tokens_per_device_second"] > 0
        dev = steady_state_deviation(rep)
        assert dev is not None and dev <= 0.10, (
            f"goodput deviated {dev:.1%} from steady state; timeline="
            f"{[(b['t_start'], b['goodput_tps']) for b in rep['timeline']]}")

    def test_spray_full_scale_trips_lru_evictions(self):
        """6000 sprayed tenant ids blow through MAX_TRACKED: the
        registry's LRU bound evicts and the new counter proves the
        churn; the quota edge sheds every second turn."""
        rep = run_scenario("adversarial_id_spray_quota_probe", scale=1.0)
        assert rep["tenancy"]["registry_evictions"] > 0
        assert rep["requests"]["shed"] > 0
        assert rep["tenancy"]["rejections"]["rate"] == \
            rep["requests"]["shed"]
        reg = get_tenant_registry()
        assert reg.evictions_total == rep["tenancy"]["registry_evictions"]
