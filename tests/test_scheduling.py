"""Resource scheduler + autoscaler + topology tests.

The reference has ZERO coverage for internal/scheduler (SURVEY.md §4);
these tests cover the ported surface plus the TPU generalisation."""

import time

import pytest

from llmq_tpu.core.config import ResourceSchedulerConfig, SchedulerConfig
from llmq_tpu.core.errors import NoResourceError
from llmq_tpu.core.types import Message, Priority
from llmq_tpu.loadbalancer import Endpoint, LoadBalancer
from llmq_tpu.core.config import LoadBalancerConfig
from llmq_tpu.queueing.queue_manager import QueueManager
from llmq_tpu.scheduling import (
    Autoscaler,
    Resource,
    ResourceRequest,
    ResourceScheduler,
    ResourceStatus,
    ResourceType,
    TpuTopology,
)


def chip_resource(rid="r0", chips=8.0, hbm=128.0, **kw):
    return Resource(
        id=rid,
        capabilities={"tpu"},
        capacity={ResourceType.CHIP: chips, ResourceType.HBM_GB: hbm},
        **kw,
    )


def chip_request(chips=4.0, hbm=64.0, **kw):
    return ResourceRequest(
        capabilities={"tpu"},
        amounts={ResourceType.CHIP: chips, ResourceType.HBM_GB: hbm},
        **kw,
    )


class TestTopology:
    def test_declare_v5e8(self):
        topo = TpuTopology.declare(8, kind="v5e")
        assert topo.num_chips == 8
        assert topo.total_hbm_gb == 128.0

    def test_declare_multihost(self):
        # BASELINE config #5: v5e-16 over 2 hosts.
        topo = TpuTopology.declare(16, num_hosts=2, kind="v5e")
        assert topo.num_hosts == 2
        assert len(topo.chips_on_host(0)) == 8
        assert len(topo.chips_on_host(1)) == 8

    def test_discover_on_cpu_mesh(self):
        # conftest forces 8 virtual CPU devices.
        topo = TpuTopology.discover()
        assert topo.num_chips == 8


class TestAllocation:
    def test_allocate_and_release(self, fake_clock):
        rs = ResourceScheduler(clock=fake_clock)
        rs.register_resource(chip_resource())
        alloc = rs.request_resource_now(chip_request())
        r = rs.get_resource("r0")
        assert r.used[ResourceType.CHIP] == 4.0
        assert r.load == pytest.approx(0.5)
        rs.release_allocation(alloc.id, alloc.token)
        assert r.used[ResourceType.CHIP] == 0.0
        assert r.load == 0.0

    def test_bad_token_rejected(self, fake_clock):
        rs = ResourceScheduler(clock=fake_clock)
        rs.register_resource(chip_resource())
        alloc = rs.request_resource_now(chip_request())
        with pytest.raises(PermissionError):
            rs.release_allocation(alloc.id, "wrong")

    def test_lowest_load_wins(self, fake_clock):
        rs = ResourceScheduler(clock=fake_clock)
        busy = chip_resource("busy")
        busy.used = {ResourceType.CHIP: 6.0, ResourceType.HBM_GB: 96.0}
        rs.register_resource(busy)
        rs.register_resource(chip_resource("idle"))
        alloc = rs.request_resource_now(chip_request(chips=2.0, hbm=32.0))
        assert alloc.resource_id == "idle"

    def test_capability_filter(self, fake_clock):
        rs = ResourceScheduler(clock=fake_clock)
        rs.register_resource(chip_resource())  # caps={"tpu"}
        req = chip_request()
        req.capabilities = {"tpu", "fp8"}
        with pytest.raises(NoResourceError):
            rs.request_resource_now(req)

    def test_capacity_filter(self, fake_clock):
        rs = ResourceScheduler(clock=fake_clock)
        rs.register_resource(chip_resource(chips=2.0, hbm=32.0))
        with pytest.raises(NoResourceError):
            rs.request_resource_now(chip_request(chips=4.0, hbm=64.0))

    def test_offline_excluded(self, fake_clock):
        rs = ResourceScheduler(clock=fake_clock)
        r = chip_resource()
        r.status = ResourceStatus.OFFLINE
        rs.register_resource(r)
        with pytest.raises(NoResourceError):
            rs.request_resource_now(chip_request())


class TestPendingQueue:
    def test_queued_then_allocated_on_release(self, fake_clock):
        rs = ResourceScheduler(clock=fake_clock)
        rs.register_resource(chip_resource())
        first = rs.request_resource_now(chip_request(chips=8.0, hbm=128.0))
        second = chip_request(chips=8.0, hbm=128.0)
        assert rs.request_resource(second) is None
        assert rs.pending_count() == 1
        rs.release_allocation(first.id, first.token)  # triggers pending drain
        assert rs.pending_count() == 0
        assert rs.get_allocation_for_request(second.id) is not None

    def test_priority_order(self, fake_clock):
        rs = ResourceScheduler(clock=fake_clock)
        rs.register_resource(chip_resource())
        blocker = rs.request_resource_now(chip_request(chips=8.0, hbm=128.0))
        low = chip_request(chips=8.0, hbm=128.0, priority=Priority.LOW)
        rt = chip_request(chips=8.0, hbm=128.0, priority=Priority.REALTIME)
        rs.request_resource(low)
        rs.request_resource(rt)
        rs.release_allocation(blocker.id, blocker.token)
        # Realtime wins the freed capacity despite arriving later.
        assert rs.get_allocation_for_request(rt.id) is not None
        assert rs.get_allocation_for_request(low.id) is None

    def test_pending_timeout_no_panic(self, fake_clock):
        # The reference panics here (resource_scheduler.go:454 reads
        # metadata["queuedAt"] that is never written).
        rs = ResourceScheduler(clock=fake_clock)
        rs.register_resource(chip_resource(chips=1.0, hbm=16.0))
        req = chip_request(chips=8.0, hbm=128.0, timeout=5.0)
        rs.request_resource(req)
        fake_clock.advance(6.0)
        rs.process_pending_once()
        assert rs.pending_count() == 0  # expired, cleanly


class TestMonitor:
    def test_heartbeat_timeout_offline_and_recovery(self, fake_clock):
        cfg = ResourceSchedulerConfig(heartbeat_timeout=30.0)
        rs = ResourceScheduler(cfg, clock=fake_clock)
        rs.register_resource(chip_resource())
        fake_clock.advance(31.0)
        out = rs.run_monitor_once()
        assert out["offline"] == 1
        assert rs.get_resource("r0").status == ResourceStatus.OFFLINE
        rs.heartbeat("r0")
        assert rs.get_resource("r0").status == ResourceStatus.ONLINE

    def test_allocation_expiry_reclaims(self, fake_clock):
        cfg = ResourceSchedulerConfig(allocation_timeout=10.0)
        rs = ResourceScheduler(cfg, clock=fake_clock)
        rs.register_resource(chip_resource())
        rs.request_resource_now(chip_request())
        rs.heartbeat("r0")
        fake_clock.advance(11.0)
        rs.heartbeat("r0")
        out = rs.run_monitor_once()
        assert out["expired_allocations"] == 1
        assert rs.get_resource("r0").load == 0.0

    def test_pinned_allocation_never_expires(self, fake_clock):
        """A serving engine's chip allocation (metadata pinned=True)
        outlives allocation_timeout — it is released only explicitly
        (the r3 verdict's 'topology/scheduler inert' fix: the serve
        entrypoint holds its chips this way)."""
        cfg = ResourceSchedulerConfig(allocation_timeout=10.0)
        rs = ResourceScheduler(cfg, clock=fake_clock)
        rs.register_resource(chip_resource())
        alloc = rs.request_resource_now(
            chip_request(metadata={"pinned": True}))
        rs.heartbeat("r0")
        fake_clock.advance(1000.0)
        rs.heartbeat("r0")
        out = rs.run_monitor_once()
        assert out["expired_allocations"] == 0
        assert rs.get_resource("r0").used[ResourceType.CHIP] == 4.0
        rs.release_allocation(alloc.id, alloc.token)
        assert rs.get_resource("r0").load == 0.0

    def test_autoscale_actuators_fire(self, fake_clock):
        ups, downs = [], []
        cfg = ResourceSchedulerConfig(scale_up_load=0.8, scale_down_load=0.2,
                                      scale_cooldown=100.0)
        rs = ResourceScheduler(cfg, clock=fake_clock,
                               scale_up_fn=ups.append, scale_down_fn=downs.append)
        r = chip_resource()
        rs.register_resource(r)
        r.used = {ResourceType.CHIP: 8.0, ResourceType.HBM_GB: 128.0}
        fake_clock.advance(200.0)
        rs.heartbeat("r0")
        rs.run_monitor_once()
        assert len(ups) == 1
        r.used = {}
        fake_clock.advance(200.0)
        rs.heartbeat("r0")
        rs.run_monitor_once()
        assert len(downs) == 1


class TestTopologyCarving:
    def test_register_topology_resources(self, fake_clock):
        rs = ResourceScheduler(clock=fake_clock)
        topo = TpuTopology.declare(16, num_hosts=2, kind="v5e")
        rows = rs.register_topology_resources(topo, chips_per_resource=8)
        assert len(rows) == 2
        assert rows[0].capacity[ResourceType.CHIP] == 8.0
        assert rows[0].capacity[ResourceType.HBM_GB] == 128.0
        assert rs.get_stats()["topology"]["num_chips"] == 16


class TestAutoscaler:
    def _setup(self, fake_clock, strategy="dynamic", pending=0):
        qm = QueueManager("as", clock=fake_clock, enable_metrics=False)
        for _ in range(pending):
            qm.push_message(Message())
        lb = LoadBalancer(LoadBalancerConfig(health_check_interval=0),
                          clock=fake_clock)
        cfg = SchedulerConfig(strategy=strategy, scale_up_threshold=10,
                              scale_down_threshold=1, min_endpoints=1,
                              max_endpoints=3, cooldown=0.0)
        provisioned = []

        def provision(seq):
            ep = Endpoint(id=f"auto-{seq}", url=f"local://auto-{seq}")
            provisioned.append(ep)
            return ep

        decommissioned = []
        a = Autoscaler(qm, lb, cfg, provision_fn=provision,
                       decommission_fn=decommissioned.append, clock=fake_clock)
        return qm, lb, a, provisioned, decommissioned

    def test_dynamic_scale_up_actuates(self, fake_clock):
        qm, lb, a, prov, _ = self._setup(fake_clock, pending=20)
        lb.add_endpoint(Endpoint(id="seed"))
        out = a.run_once()
        assert out["action"] == "up"
        assert len(lb.endpoints()) == 2
        assert len(prov) == 1

    def test_dynamic_scale_down_actuates(self, fake_clock):
        qm, lb, a, _, deco = self._setup(fake_clock, pending=0)
        lb.add_endpoint(Endpoint(id="seed-0"))
        lb.add_endpoint(Endpoint(id="seed-1"))
        out = a.run_once()
        assert out["action"] == "down"
        assert len(lb.endpoints()) == 1
        assert len(deco) == 1

    def test_respects_min_max(self, fake_clock):
        qm, lb, a, _, _ = self._setup(fake_clock, pending=0)
        lb.add_endpoint(Endpoint(id="only"))
        assert a.run_once()["action"] == "none"  # already at min

    def test_cooldown(self, fake_clock):
        qm, lb, a, _, _ = self._setup(fake_clock, pending=20)
        a.config.cooldown = 60.0
        lb.add_endpoint(Endpoint(id="seed"))
        assert a.run_once()["action"] == "up"
        assert a.run_once()["action"] == "cooldown"
        fake_clock.advance(61.0)
        assert a.run_once()["action"] == "up"

    def test_adaptive_business_hours(self, fake_clock):
        qm, lb, a, prov, _ = self._setup(fake_clock, strategy="adaptive")
        lb.add_endpoint(Endpoint(id="seed"))
        a._localtime = lambda: time.struct_time((2026, 7, 29, 11, 0, 0, 2, 210, 0))
        out = a.run_once()   # Wednesday 11:00 → near-max endpoints
        assert out["action"] == "up"
        assert len(lb.endpoints()) == 2  # max-1 = 2

    def test_hybrid_applies_weights(self, fake_clock):
        qm, lb, a, _, _ = self._setup(fake_clock, strategy="hybrid", pending=5)
        fast = Endpoint(id="fast", response_time=0.1)
        slow = Endpoint(id="slow", response_time=1.0)
        lb.add_endpoint(fast)
        lb.add_endpoint(slow)
        a.run_once()
        assert fast.weight == 1.0
        assert slow.weight == pytest.approx(0.1)
