"""Speculation plane (llmq_tpu/speculation/, docs/performance.md
"Speculative decoding"): the n-gram drafter, the k-step verify window
with device-resident sampling, accept/rollback through the paged
allocator, and the equivalence contract — with speculation ON the
committed per-request streams are TOKEN-FOR-TOKEN identical to the
plane off, on echo and CPU-JAX engines, across mixed-batch configs,
prefix continuation, the 2-deep async pipeline, preemption and chaos
crash recovery under the invariant checker. The echo executor's
``verify_accept_cap`` seam drives the reject/EOS-mid-window state
machine deterministically without hardware; the KV rollback edges
(page-boundary reject, same-window page return, dp universes) are
pinned against the allocator; attribution through verify windows keeps
the usage-ledger and critical-path conservation invariants within 2 %.
``executor.speculation.enabled: false`` is a hard off-switch: no
drafter exists, no stats block appears, streams are byte-identical to
a pre-speculation engine."""

from __future__ import annotations

import numpy as np
import pytest

import jax

from llmq_tpu import chaos
from llmq_tpu.chaos import InvariantChecker
from llmq_tpu.core.config import (AsyncPipelineConfig, ChaosConfig,
                                  KVTieringConfig, MixedBatchConfig,
                                  PrefixCacheConfig, SpeculationConfig,
                                  SupervisorConfig)
from llmq_tpu.core.types import Priority
from llmq_tpu.engine.engine import GenRequest, InferenceEngine
from llmq_tpu.engine.executor import (EchoExecutor, JaxExecutor,
                                      verify_host_ncommit)
from llmq_tpu.engine.kv_allocator import PageAllocator
from llmq_tpu.engine.supervisor import EngineSupervisor
from llmq_tpu.engine.tokenizer import ByteTokenizer
from llmq_tpu.models.llama import get_config, init_params
from llmq_tpu.speculation import NgramDrafter, propose_ngram

pytestmark = [pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")]


def spec_cfg(k=4, ngram=3, device_sampling=True):
    return SpeculationConfig(enabled=True, draft_k=k, ngram_max=ngram,
                             device_sampling=device_sampling)


def pipe_cfg(depth=2):
    return AsyncPipelineConfig(enabled=True, depth=depth,
                               completion_workers=1)


def make_echo_engine(spec=None, pipe=None, slots=4, chunk=4,
                     num_pages=256, name="spectest", metrics=False,
                     **kw):
    tok = ByteTokenizer()
    on = pipe is not None and pipe.enabled
    ex = EchoExecutor(batch_size=slots, page_size=8, num_pages=num_pages,
                      max_pages_per_seq=16, eos_id=tok.eos_id,
                      chunk_size=chunk, mixed_prefill_slices=2,
                      mixed_slice_tokens=8, async_chunks=on)
    eng = InferenceEngine(ex, tok, enable_metrics=metrics, name=name,
                          max_decode_steps=64, speculation=spec,
                          async_pipeline=pipe, **kw)
    return eng, ex


WAVE = [
    # Repetitive prompts: the echo stream replays them, so the n-gram
    # lookup has real structure to exploit (acceptance > 0).
    ("hello world hello world hello tokens " * 3, Priority.NORMAL),
    ("short", Priority.REALTIME),
    ("medium sized prompt here", Priority.LOW),
    ("another quite long prompt for slicing " * 2, Priority.HIGH),
    ("fifth request", Priority.NORMAL),
]


def drive_wave(eng, wave=WAVE, conv=None, max_new=40):
    handles = []
    for i, (prompt, prio) in enumerate(wave):
        handles.append(eng.submit(GenRequest(
            id=f"r{i}", prompt=prompt, priority=prio,
            conversation_id=(conv[i] if conv else ""),
            max_new_tokens=max_new)))
        eng.step()
        eng.step()
    eng.run_until_idle()
    return handles


# -- drafter unit behavior -----------------------------------------------------


class TestNgramDrafter:
    def test_repeating_context_proposes_continuation(self):
        ctx = [1, 2, 3, 4, 1, 2, 3, 4, 1, 2, 3]
        # Suffix 3-gram (1,2,3) last occurred at index 4 → followed by
        # 4, 1, 2, 3 — capped at k.
        assert propose_ngram(ctx, 4) == [4, 1, 2, 3]
        assert propose_ngram(ctx, 2) == [4, 1]

    def test_longest_suffix_match_wins(self):
        # 1-gram suffix (9) matches at index 1 (follow: 5); the 2-gram
        # (2, 9) matches at 3 (follow: 7) — the 2-gram must win even
        # though both exist.
        ctx = [8, 9, 5, 2, 9, 7, 1, 2, 9]
        assert propose_ngram(ctx, 1) == [7]

    def test_most_recent_occurrence_wins_within_a_length(self):
        ctx = [1, 2, 5, 0, 1, 2, 6, 0, 1, 2]
        assert propose_ngram(ctx, 1, ngram_max=2) == [6]

    def test_novel_context_proposes_nothing(self):
        assert propose_ngram([1, 2, 3, 4, 5], 4) == []
        assert propose_ngram([7], 4) == []
        assert propose_ngram([], 4) == []
        assert propose_ngram([1, 2, 1, 2], 0) == []

    def test_drafter_caps_at_draft_k_and_counts(self):
        d = NgramDrafter(draft_k=2, ngram_max=3)
        got = d.propose([1, 2, 3, 1, 2, 3, 1, 2, 3])
        assert len(got) <= 2
        assert d.windows_drafted == 1
        d.propose([9, 8, 7])
        assert d.windows_empty == 1

    def test_drafter_failure_degrades_to_empty(self):
        d = NgramDrafter(draft_k=4)
        assert d.propose(None) == []     # un-sliceable context
        assert d.windows_empty == 1


# -- the accept rule (host-side oracle shared by both accept modes) -----------


class TestVerifyAcceptRule:
    def test_all_accepted_commits_whole_window(self):
        out = np.array([[5, 6, 7, 8]], np.int32)
        drafts = np.array([[5, 6, 7]], np.int32)
        n = verify_host_ncommit(out, drafts, np.array([4]), eos=-1)
        assert n.tolist() == [4]

    def test_first_mismatch_freezes_with_correction_committed(self):
        out = np.array([[5, 9, 7, 8]], np.int32)
        drafts = np.array([[5, 6, 7]], np.int32)
        # Step 0 matches draft 5; step 1 samples 9 != draft 6 — the 9
        # IS the correction and commits, nothing after it does.
        n = verify_host_ncommit(out, drafts, np.array([4]), eos=-1)
        assert n.tolist() == [2]

    def test_eos_freezes_even_when_draft_agrees(self):
        out = np.array([[5, 0, 7, 8]], np.int32)
        drafts = np.array([[5, 0, 7]], np.int32)
        n = verify_host_ncommit(out, drafts, np.array([4]), eos=0)
        assert n.tolist() == [2]

    def test_undrafted_and_inactive_rows(self):
        out = np.array([[5, 6], [9, 9]], np.int32)
        drafts = np.array([[6], [9]], np.int32)
        n = verify_host_ncommit(out, drafts, np.array([1, 0]), eos=-1)
        assert n.tolist() == [1, 0]


# -- echo equivalence: every scheduling shape ---------------------------------


class TestEchoEquivalence:
    def run(self, spec, pipe=None, **kw):
        eng, _ = make_echo_engine(spec, pipe, **kw)
        handles = drive_wave(eng)
        stats = eng.get_stats()
        eng.stop()
        return [h.result.tokens for h in handles], stats

    def test_wave_streams_identical_and_cadence_broken(self):
        on, s_on = self.run(spec_cfg())
        off, s_off = self.run(None)
        assert on == off
        sp = s_on["speculation"]
        assert sp["tokens_accepted"] > 0
        assert sp["acceptance_rate"] > 0
        # The headline: more than one token committed per host fetch.
        assert sp["readback_cadence"] > 1.0
        assert "speculation" not in s_off

    def test_2_deep_pipeline_streams_identical(self):
        on, s_on = self.run(spec_cfg(), pipe_cfg(depth=2))
        off, _ = self.run(None, pipe_cfg(depth=2))
        plain, _ = self.run(None)
        assert on == off == plain
        assert s_on["speculation"]["readback_cadence"] > 1.0

    def test_mixed_batch_config_streams_identical(self):
        mixed = MixedBatchConfig(enabled=True, prefill_token_budget=16,
                                 max_slices=2)
        on, s_on = self.run(spec_cfg(), mixed_batch=mixed)
        off, s_off = self.run(None, mixed_batch=mixed)
        assert on == off
        # Speculation forces the unfused path: the fused mixed program
        # never runs while the plane is on.
        assert s_on["mixed_batch"]["steps"] == 0
        assert s_off["mixed_batch"]["steps"] > 0

    def test_prefix_continuation_streams_identical(self):
        def run(spec):
            eng, _ = make_echo_engine(
                spec, prefix_cache=PrefixCacheConfig(enabled=True))
            out = []
            for turn in range(3):
                handles = drive_wave(
                    eng,
                    wave=[(f"turn {turn} repeats itself turn {turn} "
                           "repeats itself", Priority.NORMAL)] * 3,
                    conv=[f"c{i}" for i in range(3)], max_new=24)
                out.append([h.result.tokens for h in handles])
            hits = eng.prefix_hits
            eng.stop()
            return out, hits

        on, hits_on = run(spec_cfg())
        off, hits_off = run(None)
        assert on == off
        assert hits_on > 0 and hits_off > 0

    def test_preemption_equivalence_single_slot(self):
        def run(spec):
            eng, _ = make_echo_engine(spec, slots=1)
            low = eng.submit(GenRequest(
                id="low", prompt="background drone work " * 4,
                priority=Priority.LOW, max_new_tokens=48))
            for _ in range(6):
                eng.step()
            rt = eng.submit(GenRequest(
                id="rt", prompt="urgent realtime request",
                priority=Priority.REALTIME, max_new_tokens=8))
            eng.run_until_idle()
            eng.stop()
            return low.result.tokens, rt.result.tokens

        assert run(spec_cfg()) == run(None)

    def test_off_switch_is_a_pre_speculation_engine(self):
        eng_off, _ = make_echo_engine(SpeculationConfig(enabled=False))
        eng_none, _ = make_echo_engine(None)
        assert eng_off._drafter is None and eng_none._drafter is None
        assert not eng_off._spec_on
        out_off = [h.result.tokens for h in drive_wave(eng_off)]
        out_none = [h.result.tokens for h in drive_wave(eng_none)]
        assert out_off == out_none
        assert "speculation" not in eng_off.get_stats()
        assert eng_off.steps == eng_none.steps
        eng_off.stop()
        eng_none.stop()


class TestEchoChaosRecovery:
    @pytest.fixture(autouse=True)
    def _chaos_reset(self):
        yield
        chaos.configure(None)

    def test_crash_with_verify_window_in_flight(self):
        """Chaos ``engine.step`` crash with a verify chunk dispatched:
        the supervisor drops the snapshot, the streamed prefix stays
        monotone (no token from the dead window leaks), and a retry
        completes on the restarted engine — zero loss, zero dup."""
        inj = chaos.configure(ChaosConfig(enabled=True, seed=21))
        checker = InvariantChecker()
        eng, _ = make_echo_engine(spec_cfg(), pipe_cfg(depth=2),
                                  name="spec-chaos")
        h = eng.submit(GenRequest(id="s0",
                                  prompt="stream me through a crash " * 3,
                                  max_new_tokens=48),
                       on_token=checker.on_token("s0"))
        checker.submitted("s0")
        for _ in range(200):
            eng.step()
            if (eng._inflight
                    and len(checker._streams.get("s0", [])) >= 3):
                break
        assert eng._inflight
        inj.add_rule("engine.step", kind="crash", times=1)
        eng.start()
        import time as _t
        deadline = _t.time() + 5.0
        while eng.running and _t.time() < deadline:
            _t.sleep(0.01)
        assert not eng.running
        sup = EngineSupervisor(eng, config=SupervisorConfig(),
                               enable_metrics=False)
        assert sup.check_once()
        assert not eng._inflight
        assert h.wait(2.0)
        assert h.result.finish_reason == "error"
        checker.failed("s0")
        checker.completed("s0", tokens=h.result.tokens)
        checker._terminal["s0"].remove("completed")  # monotone check only
        h2 = eng.submit(GenRequest(id="s1",
                                   prompt="stream me through a crash " * 3,
                                   max_new_tokens=24),
                        on_token=checker.on_token("s1"))
        checker.submitted("s1")
        assert h2.wait(10.0)
        assert h2.result.finish_reason in ("eos", "length")
        eng._drain_completions()
        checker.completed("s1", tokens=h2.result.tokens)
        eng.stop()
        sup.stop()
        checker.check()
        assert eng.spec_tokens_accepted > 0


# -- the deterministic verify seam (satellite 2) ------------------------------


class TestAcceptCapSeam:
    def test_cap_zero_rejects_everything_stream_unchanged(self):
        eng, ex = make_echo_engine(spec_cfg())
        ex.verify_accept_cap = lambda slot, n_drafts: 0
        out = [h.result.tokens for h in drive_wave(eng)]
        sp = eng.get_stats()["speculation"]
        eng.stop()
        ctl, _ = make_echo_engine(None)
        ctl_out = [h.result.tokens for h in drive_wave(ctl)]
        ctl.stop()
        # Every draft rejected: the correction token IS the true next
        # token, so the stream is unchanged — but no draft ever lands.
        assert out == ctl_out
        assert sp["tokens_proposed"] > 0
        assert sp["tokens_accepted"] == 0
        assert sp["acceptance_rate"] == 0.0

    def test_cap_zero_cadence_collapses_to_one_per_row(self):
        """Single slot so the cadence is per-row: with every draft
        rejected each fetch carries exactly one committed token — the
        floor the plane exists to break, restored on demand."""
        eng, ex = make_echo_engine(spec_cfg(), slots=1)
        ex.verify_accept_cap = lambda slot, n_drafts: 0
        h = eng.submit(GenRequest(id="c0", prompt="cap cap cap cap cap",
                                  max_new_tokens=24))
        eng.run_until_idle()
        assert h.result.finish_reason in ("eos", "length")
        sp = eng.get_stats()["speculation"]
        eng.stop()
        assert sp["readback_cadence"] <= 1.0 + 1e-9

    def test_alternating_cap_changes_counts_not_streams(self):
        def run(cap):
            eng, ex = make_echo_engine(spec_cfg())
            ex.verify_accept_cap = cap
            out = [h.result.tokens for h in drive_wave(eng)]
            sp = eng.get_stats()["speculation"]
            eng.stop()
            return out, sp

        calls = {"n": 0}

        def alternating(slot, n_drafts):
            calls["n"] += 1
            return n_drafts if calls["n"] % 2 else 1

        full, sp_full = run(None)
        alt, sp_alt = run(alternating)
        assert alt == full
        assert 0 < sp_alt["tokens_accepted"] < sp_full["tokens_accepted"]
        # More rejections → more windows to finish the same streams.
        assert sp_alt["windows"] >= sp_full["windows"]

    def test_eos_inside_accepted_window(self):
        """A row whose echo stream ends mid-window: EOS rides the
        accepted run, the row finishes with reason "eos", trailing
        window steps never commit, and the pool drains to zero."""
        eng, _ = make_echo_engine(spec_cfg(k=8, ngram=2), chunk=16)
        h = eng.submit(GenRequest(id="e0",
                                  prompt="ab ab ab ab ab ab ab",
                                  max_new_tokens=64))
        eng.run_until_idle()
        assert h.result.finish_reason == "eos"
        sp = eng.get_stats()["speculation"]
        assert sp["tokens_accepted"] > 0
        ctl, _ = make_echo_engine(None, chunk=16)
        h2 = ctl.submit(GenRequest(id="e0", prompt="ab ab ab ab ab ab ab",
                                   max_new_tokens=64))
        ctl.run_until_idle()
        assert h.result.tokens == h2.result.tokens
        assert eng.allocator.used() == eng.allocator.pinned_pages()
        eng.stop()
        ctl.stop()


# -- KV rollback edges (satellite 3) ------------------------------------------


class TestKVRollback:
    def test_rejected_window_pages_return_to_pool(self):
        """cap=0 forces a rollback on every drafted window; pages
        allocated for the rejected tail (including page-boundary
        crossings) must come back — the pool never creeps and drains
        to exactly the pinned set at idle."""
        eng, ex = make_echo_engine(spec_cfg(k=6, ngram=2), chunk=8)
        ex.verify_accept_cap = lambda slot, n_drafts: 0
        freed = []
        orig_free = eng.allocator.free

        def spy_free(pages):
            freed.extend(pages)
            orig_free(pages)

        eng.allocator.free = spy_free
        handles = drive_wave(eng, wave=[
            ("xy xy xy xy xy xy xy xy xy xy", Priority.NORMAL)] * 3,
            max_new=48)
        assert all(h.result.finish_reason in ("eos", "length")
                   for h in handles)
        assert freed                      # rollbacks actually trimmed
        assert eng.allocator.used() == eng.allocator.pinned_pages()
        eng.stop()

    def test_reject_at_page_boundary_trims_exactly(self):
        """Windows sized past a page boundary with every draft
        rejected: after each reconcile the rows hold exactly
        pages_for(pos) pages — the boundary page allocated for the
        rejected tail is returned, not leaked and not double-freed."""
        eng, ex = make_echo_engine(spec_cfg(k=6, ngram=2), chunk=8)
        ex.verify_accept_cap = lambda slot, n_drafts: 0
        h = eng.submit(GenRequest(id="pb",
                                  prompt="qr qr qr qr qr qr qr qr",
                                  max_new_tokens=40))
        for _ in range(64):
            eng.step()
            for seq in eng._slots:
                if seq is not None and seq.prefilled:
                    want = PageAllocator.pages_for(seq.pos,
                                                   eng.spec.page_size)
                    assert len(seq.pages) == want, (seq.pos, seq.pages)
            if h.result is not None:
                break
        eng.run_until_idle()
        assert h.result.finish_reason in ("eos", "length")
        assert eng.allocator.used() == eng.allocator.pinned_pages()
        eng.stop()

    def test_freed_window_page_returns_to_its_dp_universe(self):
        """The allocator resolves a freed page's universe from its id:
        a page grabbed from universe 1 for a verify window that gets
        rejected goes back to universe 1's free list — never leaking
        into universe 0 (where a batch row it can't serve would grab
        it)."""
        alloc = PageAllocator(32, 8, dp_shards=2)
        before = alloc.available_by_shard()
        window = alloc.alloc(3, shard=1)
        assert window and all(alloc.shard_of(p) == 1 for p in window)
        assert alloc.available_by_shard()[1] == before[1] - 3
        alloc.free(window)                # the _spec_trim path
        assert alloc.available_by_shard() == before

    def test_speculation_with_tiering_demotion(self):
        """Speculation × kv_tiering: multi-turn conversations whose
        pins demote to the host tier between turns decode identically
        with the plane on, and the demoted blobs round-trip."""
        from llmq_tpu.core.clock import FakeClock

        def run(spec):
            clock = FakeClock()
            eng, _ = make_echo_engine(
                spec, name="spec-tier", kv_pin_ttl=5.0, clock=clock,
                kv_tiering=KVTieringConfig(enabled=True),
                prefix_cache=PrefixCacheConfig(enabled=True))
            out = []
            for turn in range(3):
                handles = drive_wave(
                    eng,
                    wave=[(f"tier turn {turn} tier turn {turn}",
                           Priority.NORMAL)] * 2,
                    conv=["cv0", "cv1"], max_new=16)
                out.append([h.result.tokens for h in handles])
                clock.advance(6.0)        # TTL reclaim → demote
                eng.step()
            stats = eng.get_stats()
            eng.stop()
            return out, stats

        on, s_on = run(spec_cfg())
        off, s_off = run(None)
        assert on == off
        assert s_on["kv_tiering"]["demotions"] > 0
        assert s_on["speculation"]["tokens_accepted"] > 0


# -- attribution conservation (satellite 1) -----------------------------------


class TestAttributionConservation:
    @pytest.fixture(autouse=True)
    def _ledger(self):
        from llmq_tpu.observability.usage import (get_usage_ledger,
                                                  reset_usage)
        reset_usage()
        get_usage_ledger().reconfigure(enabled=True, max_tenants=64)
        yield
        reset_usage()

    def test_usage_conserved_with_multi_token_commits(self):
        from llmq_tpu.observability.usage import get_usage_ledger
        led = get_usage_ledger()
        eng, _ = make_echo_engine(spec_cfg(), name="spec-usage")
        hs = [eng.submit(GenRequest(
                  id=f"u{i}", prompt="usage usage usage usage " * 2,
                  max_new_tokens=24, tenant_id=f"tenant-{i % 2}"))
              for i in range(8)]
        eng.run_until_idle()
        assert all(h.result.finish_reason in ("eos", "length")
                   for h in hs)
        # The windows genuinely carried k > 1 commits — the weighting
        # under test is the accepted-count share, not plain budgets.
        assert eng.spec_tokens_accepted > 0
        assert eng.spec_commits_total > eng.spec_windows
        measured = eng._telemetry._device.total_ms / 1e3
        accounted = led.attributed_device_s + led.unattributed_device_s
        assert measured > 0
        assert accounted == pytest.approx(measured, rel=0.02)
        eng.stop()

    def test_critical_path_segments_conserve(self):
        from llmq_tpu.observability.critical_path import get_critical_path
        from llmq_tpu.observability.recorder import get_recorder
        rec = get_recorder()
        rec.flush_metrics()
        ana = get_critical_path()
        ana.clear()
        ana.reconfigure(enabled=True, recent_capacity=256)
        try:
            eng, _ = make_echo_engine(spec_cfg(), name="spec-cp")
            hs = [eng.submit(GenRequest(
                      id=f"cp{i}", prompt="conserve conserve conserve ",
                      max_new_tokens=24))
                  for i in range(6)]
            eng.run_until_idle()
            assert all(h.result.finish_reason in ("eos", "length")
                       for h in hs)
            assert eng.spec_tokens_accepted > 0
            eng.stop()
            rec.flush_metrics()
            snap = ana.snapshot(recent=256)
            assert snap["requests"] >= 6
            assert snap["conservation_failures"] == 0
            for r in snap["recent"]:
                seg_sum = sum(r["segments_ms"].values())
                tol = max(0.02 * r["total_ms"], 0.06)
                assert abs(seg_sum - r["total_ms"]) <= tol, r
        finally:
            rec.flush_metrics()
            ana.clear()

# -- metrics families (tentpole: observability contract) ----------------------


class TestSpecMetrics:
    def test_families_exported_with_engine_label(self):
        from llmq_tpu.metrics.registry import REGISTRY, exposition
        eng, _ = make_echo_engine(spec_cfg(), name="spec-metrics",
                                  metrics=True)
        drive_wave(eng)
        eng.stop()
        exp = exposition().decode()
        for fam in ("llm_queue_spec_acceptance_rate_count",
                    "llm_queue_spec_tokens_proposed_total",
                    "llm_queue_spec_tokens_accepted_total",
                    "llm_queue_spec_readback_cadence"):
            assert f'{fam}{{engine="spec-metrics"}}' in exp, fam
        assert REGISTRY.get_sample_value(
            "llm_queue_spec_tokens_proposed_total",
            {"engine": "spec-metrics"}) > 0
        assert REGISTRY.get_sample_value(
            "llm_queue_spec_acceptance_rate_count",
            {"engine": "spec-metrics"}) > 0
        cadence = REGISTRY.get_sample_value(
            "llm_queue_spec_readback_cadence",
            {"engine": "spec-metrics"})
        assert cadence is not None and cadence > 1.0

    def test_device_snapshot_carries_speculation_block(self):
        eng, _ = make_echo_engine(spec_cfg(), name="spec-snap")
        drive_wave(eng)
        dev = eng.get_stats()["device"]
        eng.stop()
        sp = dev.get("speculation")
        assert sp is not None
        assert sp["proposed"] > 0
        assert 0.0 < sp["acceptance_rate"] <= 1.0
        assert sp["readback_cadence"] > 1.0


# -- CPU-mode JAX: the real verify programs -----------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    cfg = get_config("llama3-tiny", max_seq_len=256, vocab_size=512)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def make_jax_engine(tiny_model, spec, *, device_sampling=True, pipe=None,
                    slots=2, max_decode_steps=16):
    cfg, params = tiny_model
    tok = ByteTokenizer()
    ex = JaxExecutor(cfg, params, batch_size=slots, page_size=8,
                     num_pages=96, prefill_buckets=[16, 64],
                     eos_id=tok.eos_id, chunk_size=4,
                     speculation_draft_k=(spec.draft_k if spec else 0),
                     speculation_device_sampling=device_sampling)
    return InferenceEngine(ex, tok, enable_metrics=False,
                           max_decode_steps=max_decode_steps,
                           speculation=spec, async_pipeline=pipe)


JWAVE = [
    ("a long prompt that needs slicing into chunks", Priority.LOW),
    ("second prompt arrives", Priority.NORMAL),
    ("urgent!", Priority.REALTIME),
]


def drive_jax(eng, temps=None, max_new=12):
    handles = []
    for i, (p, prio) in enumerate(JWAVE):
        handles.append(eng.submit(GenRequest(
            id=f"j{i}", prompt=p, priority=prio, max_new_tokens=max_new,
            temperature=(temps[i] if temps else 0.0))))
        eng.step()
        eng.step()
    eng.run_until_idle()
    out = [h.result.tokens for h in handles]
    stats = eng.get_stats()
    eng.stop()
    return out, stats


class TestJaxEquivalence:
    def test_greedy_streams_identical_both_accept_modes(self, tiny_model):
        """Greedy CPU-mode JAX with admission waves and a realtime
        preemption: device-accept AND host-accept verify programs
        commit byte-identical streams to the plane being off — the
        teacher-forced decode-shaped construction, end to end."""
        off, s_off = drive_jax(make_jax_engine(tiny_model, None))
        dev, s_dev = drive_jax(
            make_jax_engine(tiny_model, spec_cfg(k=3)))
        host, s_host = drive_jax(
            make_jax_engine(tiny_model, spec_cfg(k=3),
                            device_sampling=False))
        assert dev == off
        assert host == off
        assert "speculation" not in s_off
        assert (s_dev["speculation"]["windows"]
                == s_host["speculation"]["windows"])
        assert (s_dev["speculation"]["tokens_committed"]
                == s_host["speculation"]["tokens_committed"])

    def test_pipelined_spec_streams_identical(self, tiny_model):
        on, s_on = drive_jax(
            make_jax_engine(tiny_model, spec_cfg(k=3), pipe=pipe_cfg()))
        off, _ = drive_jax(make_jax_engine(tiny_model, None))
        assert on == off
        assert s_on["speculation"]["fetches"] > 0

    def test_temperature_modes_agree(self, tiny_model):
        """Seeded temperature sampling: the committed stream is a
        function of (row, absolute position, prefix) via the fixed
        position-keyed base key — the device-accept and host-accept
        programs draw identical streams."""
        temps = [0.8, 0.9, 0.7]
        dev, _ = drive_jax(
            make_jax_engine(tiny_model, spec_cfg(k=3)), temps=temps)
        host, _ = drive_jax(
            make_jax_engine(tiny_model, spec_cfg(k=3),
                            device_sampling=False), temps=temps)
        assert dev == host


class TestJaxKVIntegrity:
    def test_rollback_leaves_committed_kv_bitwise_intact(self, tiny_model):
        """Executor-seam rollback probe (``paged_pool_window``): drive
        a slot with verify windows whose drafts are GARBAGE (every
        window rejects at step 0 and rolls back; host-accept mode even
        writes the stale tail), re-dispatching each next window from
        the committed position — then read the committed KV region out
        of the pool. It must be bitwise identical to a control executor
        that decoded sequentially, and the committed tokens must match
        the control's samples."""
        from llmq_tpu.ops.attention import paged_pool_window
        cfg, params = tiny_model
        K = 3
        B = 1
        prompt = [11, 12, 13, 14, 15, 16, 17, 18]

        def mk(draft_k, device_sampling=False):
            return JaxExecutor(cfg, params, batch_size=B, page_size=8,
                               num_pages=16, prefill_buckets=[16],
                               eos_id=-1, chunk_size=1,
                               speculation_draft_k=draft_k,
                               speculation_device_sampling=device_sampling)

        bt = np.zeros(8, np.int32)
        bt[:4] = [1, 2, 3, 4]            # 32 token positions backed

        # Control: sequential single-step decode. ``pos`` is the write
        # position of the pending token (the engine's seq.pos): prefill
        # wrote [0, len(prompt)), the sample lands at len(prompt).
        ctl = mk(0)
        tok = ctl.prefill(prompt, 0, bt, 0.0, 0)
        ctl_stream = []
        pos = len(prompt)
        for _ in range(8):
            nxt = ctl.decode(np.array([tok], np.int32),
                             np.array([pos], np.int32), bt[None, :],
                             np.zeros(1, np.float32))
            tok = int(np.asarray(nxt)[0])
            ctl_stream.append(tok)
            pos += 1

        # Speculated: garbage drafts, every window rejected at step 0
        # (ncommit == 1) — the stale tail written past the commit point
        # must never contaminate what later windows read.
        ex = mk(K, device_sampling=False)
        tok = ex.prefill(prompt, 0, bt, 0.0, 0)
        spec_stream = []
        pos = len(prompt)
        while len(spec_stream) < 8:
            drafts = np.full((B, K), 500, np.int32)   # never sampled
            out, ncommit = ex.verify_chunk(
                np.array([tok], np.int32), np.array([pos], np.int32),
                bt[None, :], np.zeros(1, np.float32), drafts,
                np.full(B, K + 1, np.int32))
            out = np.asarray(out)
            n = int(np.asarray(ncommit)[0])
            assert n == 1                 # garbage rejects immediately
            spec_stream.extend(int(t) for t in out[0, :n])
            tok = int(out[0, n - 1])
            pos += n
        assert spec_stream[:8] == ctl_stream

        # The committed KV region [0, pos) is bitwise what sequential
        # decode wrote — rollback re-writes repaired every stale
        # position. (The stale tail past ``pos`` is deliberately NOT
        # probed: it is exactly the region seq_lens masking guards.)
        end = len(prompt) + 8
        for pool in ("k", "v"):
            got = np.asarray(paged_pool_window(
                ex.cache[pool], jax.numpy.asarray(bt), 0, end))
            want = np.asarray(paged_pool_window(
                ctl.cache[pool], jax.numpy.asarray(bt), 0, end))
            np.testing.assert_array_equal(got, want)
