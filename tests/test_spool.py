"""Split-deployment spool transport (VERDICT r3 missing #3).

The reference's split compose deployment never processes anything (its
gateway and queue-manager build independent in-process queues). These
tests drive the real transport end-to-end: producer publish → consumer
claim → local queue → worker/engine → done-ack → collector, plus the
at-least-once guarantees (claim mutual exclusion, stale-claim
reclamation, poison parking) and the App-level gateway↔consumer wiring.
"""

import os
import threading
import time


from llmq_tpu.core.types import Message, MessageStatus, Priority
from llmq_tpu.queueing.queue_manager import QueueManager
from llmq_tpu.queueing.spool import (SpoolCollector, SpoolConsumer,
                                     SpoolProducer, pending_files)
from llmq_tpu.queueing.worker import Worker


class TestSpoolCore:
    def test_publish_claim_deliver_ack_collect(self, tmp_path):
        sd = str(tmp_path / "spool")
        prod = SpoolProducer(sd)
        got = []
        cons = SpoolConsumer(sd, lambda q, m: got.append((q, m)))
        m = Message(id="m1", content="hello", priority=Priority.HIGH)
        prod.push(m, "high")
        assert pending_files(sd)
        assert cons.run_once() == 1
        assert not pending_files(sd)
        (qname, delivered), = got
        assert qname == "high"
        assert delivered.id == "m1" and delivered.content == "hello"
        assert delivered.priority == Priority.HIGH

        delivered.response = "world"
        delivered.status = MessageStatus.COMPLETED
        cons.ack_done(delivered)
        done = []
        coll = SpoolCollector(sd, done.append)
        assert coll.run_once() == 1
        assert done[0].id == "m1" and done[0].response == "world"
        assert coll.run_once() == 0        # ack consumed exactly once

    def test_priority_order_preserved_across_processes(self, tmp_path):
        sd = str(tmp_path / "spool")
        prod = SpoolProducer(sd)
        for i, prio in enumerate([Priority.LOW, Priority.REALTIME,
                                  Priority.NORMAL, Priority.HIGH]):
            prod.push(Message(id=f"m{i}", content="x", priority=prio))
        order = []
        cons = SpoolConsumer(sd, lambda q, m: order.append(m.priority))
        cons.run_once()
        assert order == sorted(order)      # realtime first, low last

    def test_claim_mutual_exclusion(self, tmp_path):
        sd = str(tmp_path / "spool")
        prod = SpoolProducer(sd)
        for i in range(20):
            prod.push(Message(id=f"m{i}", content="x"))
        seen = []
        lock = threading.Lock()

        def deliver(q, m):
            with lock:
                seen.append(m.id)

        consumers = [SpoolConsumer(sd, deliver, consumer_id=f"c{i}")
                     for i in range(3)]
        threads = [threading.Thread(target=c.run_once) for c in consumers]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(seen) == sorted(f"m{i}" for i in range(20))
        assert len(seen) == len(set(seen))  # nobody double-claimed

    def test_stale_claim_reclaimed(self, tmp_path):
        sd = str(tmp_path / "spool")
        prod = SpoolProducer(sd)
        prod.push(Message(id="m1", content="x"))

        died = SpoolConsumer(sd, lambda q, m: (_ for _ in ()).throw(
            KeyboardInterrupt()), consumer_id="dead", claim_ttl=0.1)
        # Simulate a consumer that claimed then died: rename by hand.
        name = pending_files(sd)[0]
        os.rename(os.path.join(sd, name),
                  os.path.join(sd, f"{name}.dead.claim"))
        assert not pending_files(sd)
        time.sleep(0.15)
        got = []
        cons = SpoolConsumer(sd, lambda q, m: got.append(m),
                             consumer_id="alive", claim_ttl=0.1)
        assert cons.run_once() == 1        # reclaimed + delivered
        assert got[0].id == "m1"
        del died

    def test_poison_file_parked_not_wedging(self, tmp_path):
        sd = str(tmp_path / "spool")
        prod = SpoolProducer(sd)
        with open(os.path.join(sd, "0-000-000001-bad.msg"), "w") as f:
            f.write("{not json")
        prod.push(Message(id="good", content="x"))
        got = []
        cons = SpoolConsumer(sd, lambda q, m: got.append(m.id))
        cons.run_once()
        assert got == ["good"]
        assert any(n.endswith(".poison") for n in os.listdir(sd))


class TestSplitDeployment:
    def test_gateway_to_consumer_roundtrip(self, tmp_path):
        """Two queue planes in one test process, connected ONLY by the
        spool directory — the split compose topology: gateway pushes →
        relay → spool → consumer → worker → ack → collector updates the
        gateway's message."""
        sd = str(tmp_path / "spool")

        # Gateway side.
        gw = QueueManager("gateway", enable_metrics=False)
        prod = SpoolProducer(sd)
        msg = Message(id="m1", content="ping", timeout=30.0)
        gw.push_message(msg)
        for m in gw.drain_in_priority_order(10):
            prod.push(m)

        # Consumer side: separate manager + worker + "engine".
        cm = QueueManager("consumer", enable_metrics=False)
        cons = SpoolConsumer(sd, lambda q, m: cm.push_message(m, q))

        def process(ctx, m):
            m.response = m.content + " pong"
            ack = Message.from_dict(m.to_dict())
            ack.status = MessageStatus.COMPLETED
            cons.ack_done(ack)

        w = Worker("w0", cm, process)
        assert cons.run_once() == 1
        w.process_batch()

        # Gateway collects the result.
        done = []
        coll = SpoolCollector(sd, done.append)
        assert coll.run_once() == 1
        assert done[0].response == "ping pong"
        assert done[0].status == MessageStatus.COMPLETED

    def test_app_level_split_wiring(self, tmp_path):
        """The actual entrypoint wiring: a gateway App and a
        queue-manager App (echo engine) sharing only spool_dir."""
        from llmq_tpu.__main__ import App
        from llmq_tpu.core.config import default_config

        sd = str(tmp_path / "spool")
        gcfg = default_config()
        gcfg.queue.spool_dir = sd
        gcfg.metrics.enabled = False
        gcfg.loadbalancer.health_check_interval = 0
        gateway = App(gcfg, with_api=True, with_workers=False,
                      with_engine=False)

        ccfg = default_config()
        ccfg.queue.spool_dir = sd
        ccfg.metrics.enabled = False
        ccfg.loadbalancer.health_check_interval = 0
        ccfg.queue.worker.process_interval = 0.01
        consumer = App(ccfg, with_api=False, with_workers=True,
                       with_engine=True)
        # Don't bind the API port; start only the moving parts we need.
        consumer.start()
        gateway.spool_collector.start()
        gateway._spool_relay.start()
        try:
            mgr = gateway.factory.get_queue_manager("standard")
            msg = Message(id="e2e", content="split hello", timeout=30.0)
            gateway.message_store.record(msg)
            mgr.push_message(msg)
            deadline = time.time() + 15.0
            while (msg.status != MessageStatus.COMPLETED
                   and time.time() < deadline):
                time.sleep(0.05)
            assert msg.status == MessageStatus.COMPLETED
            assert msg.response          # echo of the prompt
            assert msg.metadata["usage"]["completion_tokens"] > 0
            # Gateway queue stats saw the completion.
            stats = mgr.get_stats("normal")
            assert stats.completed_count == 1
        finally:
            gateway._stop.set()
            gateway.spool_collector.stop()
            consumer.stop()
