"""Store fault domain (llmq_tpu/conversation/resilience.py,
docs/robustness.md "Store fault domain"): bounded deadlines, seeded
retry, the store-scoped breaker + timeout-degraded rung, chaos points
``store.get``/``store.put``/``store.delete``/``store.kv`` compiled
into the real seam, and every consumer's degraded ladder rung:

- wrapper units: deadline → StoreOpTimeout, retry classification
  (sqlite locked / connection resets only), breaker trip → fast
  StoreDegradedError shed → half-open probe → recovery callbacks;
- the timeout-degraded rung for slow-not-dead (brownout) stores —
  timeout-neutral for the breaker, one probe per ``probe_interval_s``;
- state manager: cache-only reads + journaled write-behind while
  degraded, replay buffer bound, drain on recovery;
- tiering: ``_store_ok`` gates spill/promote off a degraded store;
- exchange: publish skips while degraded, claim respects the
  ``claim_ttl_s`` wall budget under injected store latency (the
  promote lane never stalls — recompute instead);
- SqliteStore bounded ``database is locked`` retry (unit + a
  cross-connection 4-thread contention run);
- WAL OSError rung: admission-path faults shed an explicit 503
  (+ Retry-After) through the REST layer, worker-side faults are
  counted + logged and the loop survives;
- /health ``store`` block presence (and absence for raw backends),
  the new metric families, the off-switch;
- acceptance: a store blackout mid-workload on echo AND CPU-JAX
  engines (tiering + exchange enabled, async pipeline depth 2) —
  zero loss/dup, bounded per-request latency while the store is dead,
  store-tier hits resume + the replay buffer drains after recovery.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time
import urllib.error
import urllib.request

import pytest

from llmq_tpu import chaos
from llmq_tpu.api.server import ApiServer
from llmq_tpu.chaos import InvariantChecker
from llmq_tpu.conversation.persistence import InMemoryStore, SqliteStore
from llmq_tpu.conversation.resilience import (ResilientKVStore,
                                              ResilientStore,
                                              StoreDegradedError,
                                              StoreOpTimeout, _retryable,
                                              wrap_store)
from llmq_tpu.conversation.state_manager import StateManager
from llmq_tpu.core.clock import FakeClock
from llmq_tpu.core.config import (AsyncPipelineConfig, BreakerConfig,
                                  ChaosConfig, ConversationConfig,
                                  KVTieringConfig, PrefixCacheConfig,
                                  StoreResilienceConfig, default_config)
from llmq_tpu.core.errors import ConversationNotFoundError
from llmq_tpu.core.types import Conversation, Message
from llmq_tpu.disagg import KVExchange
from llmq_tpu.engine.engine import GenRequest, InferenceEngine
from llmq_tpu.engine.executor import EchoExecutor
from llmq_tpu.engine.tokenizer import ByteTokenizer
from llmq_tpu.queueing.queue_manager import QueueManager

pytestmark = [
    pytest.mark.chaos,
    pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning"),
]


@pytest.fixture(autouse=True)
def _chaos_reset():
    """Every scenario leaves the process with chaos DISARMED."""
    yield
    chaos.configure(None)


def _arm(seed: int, *rules) -> chaos.FaultInjector:
    inj = chaos.configure(ChaosConfig(enabled=True, seed=seed))
    for r in rules:
        inj.add_rule(**r)
    return inj


def wait_until(fn, timeout=5.0, step=0.002):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


def _rcfg(**kw) -> StoreResilienceConfig:
    """Test-tuned resilience config: tight deadline, deterministic
    (jitter-free) breaker, sub-second windows."""
    breaker = kw.pop("breaker", None) or BreakerConfig(
        enabled=True, failure_threshold=3, base_backoff=5.0,
        max_backoff=20.0, jitter=0.0)
    base = dict(enabled=True, op_timeout_s=0.05, retries=2,
                retry_base_backoff_s=0.001, retry_max_backoff_s=0.005,
                retry_jitter=0.2, timeout_threshold=2,
                probe_interval_s=10.0, seed=7)
    base.update(kw)
    return StoreResilienceConfig(breaker=breaker, **base)


class ScriptedStore:
    """InMemoryStore front whose next ``fail_times`` ops raise
    ``fail_with(...)`` and whose every op sleeps ``sleep_s`` first —
    a scriptable dead/slow (brownout) backend."""

    def __init__(self):
        self.raw = InMemoryStore()
        self.fail_with = ConnectionError
        self.fail_times = 0
        self.sleep_s = 0.0
        self.calls = []

    def _gate(self, name):
        self.calls.append(name)
        if self.sleep_s:
            time.sleep(self.sleep_s)
        if self.fail_times > 0:
            self.fail_times -= 1
            raise self.fail_with(f"scripted {name} fault")

    def save(self, conv):
        self._gate("save")
        self.raw.save(conv)

    def load(self, cid):
        self._gate("load")
        return self.raw.load(cid)

    def list_user(self, uid):
        self._gate("list_user")
        return self.raw.list_user(uid)

    def delete(self, cid):
        self._gate("delete")
        self.raw.delete(cid)

    def save_kv(self, cid, blob):
        self._gate("save_kv")
        self.raw.save_kv(cid, blob)

    def load_kv(self, cid):
        self._gate("load_kv")
        return self.raw.load_kv(cid)

    def delete_kv(self, cid):
        self._gate("delete_kv")
        self.raw.delete_kv(cid)

    def list_kv(self):
        self._gate("list_kv")
        return self.raw.list_kv()

    def close(self):
        self.raw.close()


def _conv(cid="c1", uid="u1") -> Conversation:
    return Conversation(id=cid, user_id=uid, created_at=1.0,
                        updated_at=1.0, last_active_at=1.0)


def _trip(store, inner, n=3):
    """Drive ``n`` consecutive faults through the wrapper so the
    breaker opens (retries must be 0 in the wrapper's config)."""
    inner.fail_times = n
    for _ in range(n):
        with pytest.raises(ConnectionError):
            store.load_kv("x")
    assert store.degraded


# -- wrapper units -------------------------------------------------------------


class TestWrapStore:
    def test_picks_kv_class_by_feature_detection(self):
        kv = wrap_store(InMemoryStore(), _rcfg())
        assert isinstance(kv, ResilientKVStore)
        assert hasattr(kv, "save_kv")

        class NoKV:
            def save(self, c): pass
            def load(self, cid): return None
            def list_user(self, uid): return []
            def delete(self, cid): pass
            def close(self): pass

        plain = wrap_store(NoKV(), _rcfg())
        assert isinstance(plain, ResilientStore)
        assert not isinstance(plain, ResilientKVStore)
        # Downstream hasattr-based spill detection must keep working.
        assert not hasattr(plain, "save_kv")
        kv.close()
        plain.close()

    def test_roundtrip_is_transparent(self):
        store = wrap_store(InMemoryStore(), _rcfg())
        store.save(_conv("c1"))
        loaded = store.load("c1")
        assert loaded is not None and loaded.id == "c1"
        store.save_kv("c1", b"\x00payload\xff")
        assert store.load_kv("c1") == b"\x00payload\xff"
        assert store.list_kv() == ["c1"]
        store.delete_kv("c1")
        assert store.load_kv("c1") is None
        assert list(store.list_user("u1")) == ["c1"]
        store.delete("c1")
        assert store.load("c1") is None
        assert store.totals["errors"] == 0
        store.close()

    def test_retryable_classification(self):
        assert _retryable(sqlite3.OperationalError("database is locked"))
        assert _retryable(sqlite3.OperationalError("database is busy"))
        assert not _retryable(sqlite3.OperationalError("no such table: x"))
        assert _retryable(ConnectionResetError("reset"))
        assert _retryable(ConnectionError("refused"))
        assert not _retryable(ValueError("nope"))


class TestDeadlineAndRetry:
    def test_deadline_bounds_a_slow_store(self):
        inner = ScriptedStore()
        inner.sleep_s = 0.5
        store = wrap_store(inner, _rcfg(op_timeout_s=0.05))
        t0 = time.perf_counter()
        with pytest.raises(StoreOpTimeout):
            store.load_kv("c1")
        # The caller got out at the deadline, not the backend's pace.
        assert time.perf_counter() - t0 < 0.4
        assert store.totals["timeouts"] == 1
        # Timeout-neutral rule: deadline misses never count as faults.
        assert store.resilience_stats()["breaker"]["state"] == "closed"
        store.close()

    def test_retry_on_sqlite_locked_then_success(self):
        inner = ScriptedStore()
        inner.fail_with = lambda m: sqlite3.OperationalError(
            "database is locked")
        inner.fail_times = 2
        store = wrap_store(inner, _rcfg(retries=2))
        inner.raw.save_kv("c1", b"blob")
        assert store.load_kv("c1") == b"blob"
        assert store.totals["retries"] == 2
        assert store.totals["errors"] == 0
        store.close()

    def test_retry_on_connection_reset(self):
        inner = ScriptedStore()
        inner.fail_with = ConnectionResetError
        inner.fail_times = 1
        store = wrap_store(inner, _rcfg(retries=1))
        store.save_kv("c1", b"x")
        assert inner.raw.load_kv("c1") == b"x"
        assert store.totals["retries"] == 1
        store.close()

    def test_non_retryable_fails_immediately(self):
        inner = ScriptedStore()
        inner.fail_with = ValueError
        inner.fail_times = 5
        store = wrap_store(inner, _rcfg(retries=2))
        with pytest.raises(ValueError):
            store.load_kv("c1")
        assert inner.calls.count("load_kv") == 1   # no retry burned
        assert store.totals["errors"] == 1
        assert store.totals["retries"] == 0
        store.close()

    def test_retries_are_bounded(self):
        inner = ScriptedStore()
        inner.fail_with = lambda m: sqlite3.OperationalError(
            "database is locked")
        inner.fail_times = 100
        store = wrap_store(inner, _rcfg(retries=2))
        with pytest.raises(sqlite3.OperationalError):
            store.load_kv("c1")
        assert store.totals["retries"] == 2        # 1 try + 2 retries
        assert inner.calls.count("load_kv") == 3
        store.close()


class TestBreakerAndDegradedLadder:
    def test_trip_sheds_fast_without_touching_the_backend(self):
        fk = FakeClock()
        inner = ScriptedStore()
        store = wrap_store(inner, _rcfg(retries=0), clock=fk)
        _trip(store, inner)
        dispatched = len(inner.calls)
        t0 = time.perf_counter()
        with pytest.raises(StoreDegradedError):
            store.load_kv("x")
        assert time.perf_counter() - t0 < 0.05     # no round-trip paid
        assert len(inner.calls) == dispatched      # backend never saw it
        assert store.totals["shed"] == 1
        assert store.resilience_stats()["breaker"]["state"] == "open"
        store.close()

    def test_probe_recovers_and_fires_recovery_callbacks(self):
        fk = FakeClock()
        inner = ScriptedStore()
        store = wrap_store(inner, _rcfg(retries=0), clock=fk)
        store.register_consumer("tiering")
        store.register_consumer("nonsense")        # not in the contract
        fired = []
        store.on_recovery(lambda: fired.append(1))
        _trip(store, inner)
        assert fired == []                          # not yet recovered
        fk.advance(6.0)                             # past base_backoff
        assert not store.degraded                   # window elapsed
        inner.raw.save_kv("x", b"back")
        assert store.load_kv("x") == b"back"        # half-open probe wins
        assert fired == [1]
        st = store.resilience_stats()
        assert st["breaker"]["state"] == "closed"
        assert st["consumers"] == ["tiering"]       # closed enum enforced
        assert st["degraded"] is False
        store.close()

    def test_timeout_degraded_rung_probes_on_interval(self):
        fk = FakeClock()
        inner = ScriptedStore()
        inner.sleep_s = 0.2
        store = wrap_store(
            inner, _rcfg(op_timeout_s=0.05, timeout_threshold=2,
                         probe_interval_s=10.0),
            clock=fk)
        for _ in range(2):
            t0 = time.perf_counter()
            with pytest.raises(StoreOpTimeout):
                store.load_kv("c1")
            assert time.perf_counter() - t0 < 0.4   # bounded every time
        assert store.degraded
        assert store.resilience_stats()["timeout_degraded"] is True
        # The breaker stayed closed: timeouts are rung fuel, not faults.
        assert store.resilience_stats()["breaker"]["state"] == "closed"
        # Inside the probe window: shed without dispatching.
        dispatched = len(inner.calls)
        with pytest.raises(StoreDegradedError):
            store.load_kv("c1")
        assert len(inner.calls) == dispatched
        # Past the window the probe goes through; a success clears it.
        fk.advance(11.0)
        inner.sleep_s = 0.0
        inner.raw.save_kv("c1", b"ok")
        assert store.load_kv("c1") == b"ok"
        assert not store.degraded
        store.close()


class TestChaosPoints:
    def test_store_kv_error_fires_in_the_seam(self):
        store = wrap_store(InMemoryStore(), _rcfg())
        _arm(41, {"point": "store.kv", "kind": "error", "times": 1})
        with pytest.raises(chaos.ChaosFault):
            store.load_kv("c1")
        assert store.totals["errors"] == 1
        store.load_kv("c1")                         # rule exhausted
        store.close()

    def test_match_filters_on_op(self):
        store = wrap_store(InMemoryStore(), _rcfg())
        _arm(42, {"point": "store.kv", "kind": "error", "times": 1,
                  "match": {"op": "kv_put"}})
        assert store.load_kv("c1") is None          # kv_get: filtered
        with pytest.raises(chaos.ChaosFault):
            store.save_kv("c1", b"x")
        store.close()

    def test_injected_latency_is_bounded_by_the_deadline(self):
        """The chaos seam fires INSIDE the pool worker, so a 300ms
        injected brownout hits the same 50ms deadline a slow real
        backend would."""
        store = wrap_store(InMemoryStore(), _rcfg(op_timeout_s=0.05))
        _arm(43, {"point": "store.get", "kind": "latency",
                  "latency_ms": 300, "times": 1})
        t0 = time.perf_counter()
        with pytest.raises(StoreOpTimeout):
            store.load("c1")
        assert time.perf_counter() - t0 < 0.25
        store.close()


# -- state manager degraded mode -----------------------------------------------


class TestStateManagerDegraded:
    def _stack(self, **rkw):
        fk = FakeClock()
        inner = ScriptedStore()
        store = wrap_store(inner, _rcfg(retries=0, **rkw), clock=fk)
        sm = StateManager(ConversationConfig(persist=True), store=store)
        return fk, inner, store, sm

    def test_writes_journal_and_reads_serve_cache_while_degraded(self):
        fk, inner, store, sm = self._stack()
        # Three failing saves trip the breaker; each is journaled.
        inner.fail_times = 3
        for i in range(3):
            sm.create("u1", conversation_id=f"c{i}")
        assert store.degraded
        assert sm.replay_pending() == 3
        # A degraded-mode write never pays a store round-trip.
        dispatched = len(inner.calls)
        sm.create("u1", conversation_id="c3")
        assert len(inner.calls) == dispatched
        assert sm.replay_pending() == 4
        # Reads: cached conversations serve, unknown ids fail fast
        # without a store hit.
        assert sm.get("c0").id == "c0"
        with pytest.raises(ConversationNotFoundError):
            sm.get("never-existed")
        assert len(inner.calls) == dispatched
        store.close()

    def test_recovery_drains_the_replay_buffer(self):
        fk, inner, store, sm = self._stack()
        inner.fail_times = 3
        for i in range(3):
            sm.create("u1", conversation_id=f"c{i}")
        assert sm.replay_pending() == 3
        fk.advance(6.0)                            # breaker window over
        sm.create("u1", conversation_id="c3")      # probe save succeeds
        assert sm.replay_pending() == 0
        for i in range(4):
            assert inner.raw.load(f"c{i}") is not None
        store.close()

    def test_replay_buffer_is_bounded(self):
        fk, inner, store, sm = self._stack(replay_buffer=4)
        inner.fail_times = 3
        for i in range(10):
            sm.create("u1", conversation_id=f"c{i}")
        assert store.degraded
        assert sm.replay_pending() == 4            # deque maxlen
        store.close()

    def test_consumers_registered(self):
        _, _, store, sm = self._stack()
        assert set(store.resilience_stats()["consumers"]) == {
            "state", "placement"}
        store.close()


# -- tiering degraded mode -----------------------------------------------------


class TestTieringDegraded:
    def test_store_ok_gates_off_a_degraded_store(self):
        import numpy as np

        from llmq_tpu.tiering import KVTieringPlane

        class _Exec:
            def kv_page_spec(self):
                return [((2, 4, 8), np.dtype(np.float32))]

            def export_kv_pages(self, pages):
                return [np.zeros((2, len(pages), 8), np.float32)]

            def import_kv_pages(self, pages, leaves):
                pass

        fk = FakeClock()
        inner = ScriptedStore()
        store = wrap_store(inner, _rcfg(retries=0), clock=fk)
        plane = KVTieringPlane(KVTieringConfig(enabled=True), "p", _Exec())
        plane.store = store
        assert "tiering" in store.resilience_stats()["consumers"]
        assert plane._store_ok()                   # noqa: SLF001
        _trip(store, inner)
        # Degraded: spill/store-promote paths gate off → demotions park
        # in host, store-tier promotes recompute instead of blocking.
        assert not plane._store_ok()               # noqa: SLF001
        fk.advance(6.0)
        assert plane._store_ok()                   # noqa: SLF001
        plane.stop()
        store.close()


# -- exchange degraded mode + claim wall budget (satellite) --------------------


class TestExchangeDegraded:
    def test_publish_skips_while_degraded(self):
        fk = FakeClock()
        inner = ScriptedStore()
        store = wrap_store(inner, _rcfg(retries=0), clock=fk)
        x = KVExchange(store, role="prefill", metrics=False)
        assert "exchange" in store.resilience_stats()["consumers"]
        _trip(store, inner)
        x.publish("c1", [], [], meta={"tokens": [1, 2, 3]})
        assert inner.raw.list_kv() == []           # no round-trip paid
        assert x.totals["fallback"] == 1
        assert x.totals["published"] == 0
        store.close()

    def test_claim_under_injected_latency_degrades_to_recompute(self):
        """The satellite pin: a brownout (injected store latency) at
        claim time must respect the wall budget and fall back to
        recompute — the promote lane never blocks on the store."""
        store = wrap_store(InMemoryStore(), _rcfg(op_timeout_s=0.05))
        x = KVExchange(store, role="decode", claim_ttl_s=2.0,
                       metrics=False)
        x.publish("c1", [], [], meta={"tokens": [1, 2]})
        _arm(51, {"point": "store.kv", "kind": "latency",
                  "latency_ms": 400, "times": 1, "match": {"op": "kv_get"}})
        t0 = time.perf_counter()
        assert x.claim("c1") is None               # recompute, not stall
        assert time.perf_counter() - t0 < 0.35
        assert x.totals["fallback"] == 1
        # The entry survives the shed claim and is consumable after.
        got = x.claim("c1")
        assert got is not None and got[2]["tokens"] == [1, 2]
        store.close()

    def test_claim_wall_budget_on_a_raw_slow_store(self):
        """The belt for raw backends (resilience off): a claim that
        spent longer in the store than claim_ttl_s is dropped."""

        class SlowLoad(InMemoryStore):
            def load_kv(self, cid):
                time.sleep(0.08)
                return super().load_kv(cid)

        raw = SlowLoad()
        x = KVExchange(raw, role="decode", claim_ttl_s=0.05,
                       metrics=False)
        x.publish("c1", [], [], meta={"tokens": [9]})
        assert x.claim("c1") is None
        assert x.totals["fallback"] == 1
        assert raw.list_kv() == []                 # entry deleted


# -- sqlite locked retry (satellite) -------------------------------------------


class TestSqliteLockedRetry:
    def test_locked_retry_unit(self, tmp_path):
        store = SqliteStore(str(tmp_path / "u.db"))
        attempts = {"n": 0}

        def flaky():
            attempts["n"] += 1
            if attempts["n"] <= 2:
                raise sqlite3.OperationalError("database is locked")
            return "ok"

        assert store._with_locked_retry(flaky) == "ok"  # noqa: SLF001
        assert attempts["n"] == 3
        store.close()

    def test_locked_retry_is_bounded_and_selective(self, tmp_path):
        store = SqliteStore(str(tmp_path / "b.db"))
        calls = {"n": 0}

        def always_locked():
            calls["n"] += 1
            raise sqlite3.OperationalError("database is locked")

        with pytest.raises(sqlite3.OperationalError):
            store._with_locked_retry(always_locked)  # noqa: SLF001
        assert calls["n"] == 1 + store._LOCKED_RETRIES  # noqa: SLF001

        def schema_error():
            raise sqlite3.OperationalError("no such table: kv_payloads")

        calls["n"] = 0
        with pytest.raises(sqlite3.OperationalError):
            store._with_locked_retry(schema_error)   # noqa: SLF001
        store.close()

    def test_cross_connection_contention_four_threads(self, tmp_path):
        """Two independent connections (separate SqliteStore instances
        over one file) hammered by 4 threads: the busy_timeout + the
        bounded locked-retry must absorb every lock race — no
        OperationalError escapes, every write readable."""
        path = str(tmp_path / "cont.db")
        stores = [SqliteStore(path), SqliteStore(path)]
        errors = []
        stop = threading.Event()

        def worker(wid):
            st = stores[wid % 2]
            try:
                for i in range(60):
                    cid = f"w{wid}-{i % 5}"
                    st.save_kv(cid, bytes([wid]) * 1024)
                    blob = st.load_kv(cid)
                    assert blob is None or blob[:1] == bytes([wid])
                    if i % 9 == 0:
                        st.delete_kv(cid)
                    if stop.is_set():
                        return
            except Exception as e:  # noqa: BLE001 — collected for assert
                errors.append(e)
                stop.set()

        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        for st in stores:
            st.close()


# -- WAL OSError rung (satellite) ----------------------------------------------


class _Client:
    def __init__(self, port):
        self.base = f"http://127.0.0.1:{port}"

    def post(self, path, body):
        req = urllib.request.Request(
            self.base + path, data=json.dumps(body).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status, json.loads(resp.read()), dict(
                    resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read()), dict(e.headers)


class TestWalShed:
    def test_admission_path_fault_sheds_503_with_retry_after(
            self, tmp_path):
        """An ENOSPC-shaped WAL append fault on push must surface as an
        explicit 503 + Retry-After at the REST edge — the at-least-once
        promise is refused, not silently broken — and the stack keeps
        serving afterwards."""
        from llmq_tpu.queueing.factory import QueueFactory, QueueType

        cfg = default_config()
        cfg.queue.enable_metrics = False
        cfg.queue.worker.process_interval = 0.005
        cfg.loadbalancer.health_check_interval = 0.0
        cfg.queue.wal_dir = str(tmp_path)
        tok = ByteTokenizer()
        engine = InferenceEngine(
            EchoExecutor(batch_size=4, eos_id=tok.eos_id), tok,
            name="walshed", enable_metrics=False, max_decode_steps=16)
        engine.start()
        factory = QueueFactory(cfg)
        factory.create_queue_manager("standard", QueueType.STANDARD)
        server = ApiServer(cfg, queue_factory=factory, engine=engine)
        port = server.start(host="127.0.0.1", port=0)
        client = _Client(port)
        try:
            _arm(61, {"point": "wal.append", "kind": "oserror",
                      "times": 1, "match": {"op": "push"}})
            status, payload, hdrs = client.post(
                "/api/v1/messages",
                {"id": "wal0", "content": "x", "user_id": "u"})
            assert status == 503
            assert "WAL push failed" in payload["error"]
            assert payload["retry_after"] == 1.0
            assert hdrs.get("Retry-After") is not None
            # Rule exhausted: the next push is admitted normally.
            status, _, _ = client.post(
                "/api/v1/messages",
                {"id": "wal1", "content": "x", "user_id": "u"})
            assert status in (200, 202)
        finally:
            server.stop()
            factory.stop_all()
            engine.stop()

    def test_worker_side_fault_is_counted_and_loop_survives(
            self, tmp_path):
        """A WAL OSError on a worker-side op (complete) must NOT kill
        the worker loop: the op is counted in wal_errors_total{op},
        logged loudly, and processing continues (at-least-once replay
        covers the durability gap)."""
        from llmq_tpu.metrics.registry import exposition

        mgr = QueueManager("walstore",
                           wal_path=str(tmp_path / "w.wal"))
        _arm(62, {"point": "wal.append", "kind": "oserror", "times": 1,
                  "match": {"op": "complete"}})
        qname = mgr.push_message(Message(id="m0", content="x",
                                         user_id="u"))
        msg = mgr.pop_message(qname)
        mgr.complete_message(msg, 0.0, qname)       # fault swallowed
        assert mgr.total_pending() == 0
        # The manager is still fully functional after the fault.
        qname = mgr.push_message(Message(id="m1", content="x",
                                         user_id="u"))
        msg = mgr.pop_message(qname)
        mgr.complete_message(msg, 0.0, qname)
        mgr.stop()
        assert b'wal_errors_total{op="complete"} 1' in exposition()


# -- /health block + metric families + off-switch ------------------------------


class TestHealthAndMetrics:
    def _server(self, sm):
        from llmq_tpu.queueing.factory import QueueFactory, QueueType

        cfg = default_config()
        cfg.queue.enable_metrics = False
        cfg.loadbalancer.health_check_interval = 0.0
        tok = ByteTokenizer()
        engine = InferenceEngine(
            EchoExecutor(batch_size=2, eos_id=tok.eos_id), tok,
            name="storehealth", enable_metrics=False)
        engine.start()
        factory = QueueFactory(cfg)
        factory.create_queue_manager("standard", QueueType.STANDARD)
        server = ApiServer(cfg, queue_factory=factory, engine=engine,
                           state_manager=sm)
        return server, factory, engine

    def test_health_carries_store_block_when_wrapped(self):
        store = wrap_store(InMemoryStore(), _rcfg())
        sm = StateManager(ConversationConfig(persist=True), store=store)
        server, factory, engine = self._server(sm)
        port = server.start(host="127.0.0.1", port=0)
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/health")
            with urllib.request.urlopen(req, timeout=10) as resp:
                body = json.loads(resp.read())
            blk = body["store"]
            assert blk["resilience"] is True
            assert blk["degraded"] is False
            assert blk["replay_pending"] == 0
            assert set(blk["consumers"]) == {"state", "placement"}
            assert blk["breaker"]["state"] == "closed"
        finally:
            server.stop()
            factory.stop_all()
            engine.stop()
            store.close()

    def test_raw_backend_has_no_store_block(self):
        """Off-switch shape: with resilience disabled nothing is
        wrapped and pre-feature health bodies stay byte-identical."""
        cfg = default_config()
        assert cfg.store.resilience.enabled is False
        assert cfg.store.enabled is False
        raw = InMemoryStore()
        assert not hasattr(raw, "degraded")
        assert not hasattr(raw, "resilience_stats")
        sm = StateManager(ConversationConfig(persist=True), store=raw)
        assert sm._store_degraded() is False        # noqa: SLF001
        server, factory, engine = self._server(sm)
        try:
            assert server._store_block() is None    # noqa: SLF001
        finally:
            factory.stop_all()
            engine.stop()

    def test_new_metric_families_flush_at_scrape(self):
        from llmq_tpu.metrics.registry import exposition

        store = wrap_store(InMemoryStore(), _rcfg())
        store.register_consumer("exchange")
        store.save_kv("c1", b"x")
        assert store.load_kv("c1") == b"x"
        text = exposition()
        assert b"store_op_ms" in text
        assert b'store_op_ms_count{op="kv_put",outcome="ok"}' in text
        assert b"store_retries_total" in text
        assert b"store_breaker_state 0.0" in text
        assert b'store_degraded{consumer="exchange"} 0.0' in text
        # The buffer drained: totals persist, samples do not re-emit.
        assert store.totals["ops"] == 2
        store.close()


# -- acceptance: blackout mid-workload -----------------------------------------


def _accept_rcfg(seed=11) -> StoreResilienceConfig:
    """Acceptance tuning: real-clock breaker with sub-second backoff so
    recovery happens inside the test's wall budget."""
    return StoreResilienceConfig(
        enabled=True, op_timeout_s=0.25, retries=1,
        retry_base_backoff_s=0.001, retry_max_backoff_s=0.005,
        timeout_threshold=3, probe_interval_s=0.05, seed=seed,
        breaker=BreakerConfig(enabled=True, failure_threshold=3,
                              base_backoff=0.15, max_backoff=0.6,
                              jitter=0.0))


def _turn(eng, sm, checker, rid, conv, prompt, budget_s=4.0):
    """One closed-loop turn through the real submit path: invariant
    tracking + the service layer's state write + a hard wall bound (a
    dead store must never stall the hot path past its deadline)."""
    checker.submitted(rid)
    sm.add_message(conv, Message(id=rid, content=prompt, user_id="u"))
    t0 = time.perf_counter()
    h = eng.submit(GenRequest(id=rid, prompt=prompt,
                              conversation_id=conv, max_new_tokens=8))
    eng.run_until_idle()
    wall = time.perf_counter() - t0
    assert h.result is not None and h.result.finish_reason in (
        "eos", "length"), rid
    assert wall < budget_s, (
        f"{rid} took {wall:.2f}s with the store dead — hot path stalled")
    checker.completed(rid, tokens=h.result.tokens)
    return h


class TestStoreBlackoutAcceptance:
    def test_echo_engine_blackout_recovery(self):
        """The tentpole acceptance bar on the echo engine: tiering +
        exchange + state manager over ONE wrapped store, async pipeline
        depth 2; a store blackout mid-workload sheds to the degraded
        ladder (bounded latency, zero loss), and after the store comes
        back store-tier hits resume and the replay buffer drains."""
        store = wrap_store(InMemoryStore(), _accept_rcfg())
        sm = StateManager(ConversationConfig(persist=True), store=store)
        tok = ByteTokenizer()
        fclock = FakeClock()
        eng = InferenceEngine(
            EchoExecutor(batch_size=4, page_size=8, num_pages=128,
                         max_pages_per_seq=16, eos_id=tok.eos_id,
                         chunk_size=4),
            tok, name="storechaos-echo", enable_metrics=False,
            kv_pin_ttl=5.0, clock=fclock,
            kv_tiering=KVTieringConfig(enabled=True, host_capacity_mb=4,
                                       host_max_conversations=16,
                                       store_spill=True),
            prefix_cache=PrefixCacheConfig(enabled=True),
            async_pipeline=AsyncPipelineConfig(enabled=True, depth=2))
        eng.attach_conversation_manager(sm)
        x = KVExchange(store, role="unified", metrics=False)
        eng._tiering.exchange = x                   # noqa: SLF001
        checker = InvariantChecker()
        convs = [f"bc{i}" for i in range(4)]
        try:
            # Warm phase: a turn per conversation, then demote to the
            # host tier (echo is content-free — real store-tier spill
            # payloads are the JAX leg's job; here the store carries
            # state saves + the exchange).
            for i, c in enumerate(convs):
                _turn(eng, sm, checker, f"{c}.t1", c, f"warm {i} text")
            fclock.advance(6.0)
            eng.step()
            plane = eng._tiering                    # noqa: SLF001
            assert wait_until(
                lambda: sum(plane.counts().values()) == len(convs))
            _turn(eng, sm, checker, f"{convs[0]}.t2", convs[0], " more")
            pre = eng.get_stats()["kv_tiering"]["hits"]
            assert pre["host"] >= 1

            # Blackout: every store-backed plane faults at once. Every
            # turn must still complete inside its wall budget.
            _arm(71, {"point": "store.*", "kind": "error", "times": 500})
            for i, c in enumerate(convs):
                _turn(eng, sm, checker, f"{c}.t3", c, f" blackout {i}")
            for i in range(4, 8):                   # fresh arrivals too
                _turn(eng, sm, checker, f"bc{i}.t1", f"bc{i}",
                      f"new {i} during blackout")
            st = store.resilience_stats()
            assert st["breaker"]["trips"] >= 1      # breaker tripped
            assert store.totals["errors"] >= 3
            assert store.totals["shed"] > 0         # fast-fail, not hang
            assert sm.replay_pending() > 0          # writes journaled

            # Store comes back: breaker probes within its sub-second
            # backoff, recovery drains the journal.
            chaos.configure(None)
            assert wait_until(lambda: not store.degraded, timeout=5.0)
            _turn(eng, sm, checker, f"{convs[1]}.t4", convs[1], " back")
            assert wait_until(lambda: sm.replay_pending() == 0,
                              timeout=5.0)
            for c in convs:
                assert store.inner.load(c) is not None

            # Store round-trips resume: a publish→claim handoff lands
            # through the recovered store, and host-tier promotes keep
            # serving.
            x.publish("hand", [], [], meta={"tokens": [1, 2]})
            got = x.claim("hand")
            assert got is not None and got[2]["tokens"] == [1, 2]
            fclock.advance(6.0)
            eng.step()
            assert wait_until(
                lambda: sum(plane.counts().values()) >= len(convs))
            hits0 = eng.get_stats()["kv_tiering"]["hits"]["host"]
            _turn(eng, sm, checker, f"{convs[2]}.t5", convs[2], " again")
            assert eng.get_stats()["kv_tiering"]["hits"]["host"] > hits0
            checker.check()                         # zero loss/dup
        finally:
            eng.stop()
            store.close()

    def test_jax_engine_blackout_matches_baseline(self):
        """CPU-JAX leg: a conversation whose KV sat in the STORE tier
        decodes its next turn during a blackout token-for-token equal
        to a pin-resident baseline — recompute-on-promote, bounded,
        zero loss — and the plane recovers after."""
        import jax

        from llmq_tpu.engine.executor import JaxExecutor
        from llmq_tpu.models.llama import init_params, llama3_tiny

        mcfg = llama3_tiny(dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                           ffn_dim=128, vocab_size=512, max_seq_len=256)
        params = init_params(jax.random.PRNGKey(0), mcfg)

        def build(tiering, store):
            tok = ByteTokenizer()
            ex = JaxExecutor(mcfg, params, batch_size=2, page_size=8,
                             num_pages=64, prefill_buckets=[16, 64],
                             eos_id=tok.eos_id, chunk_size=4)
            fclock = FakeClock()
            eng = InferenceEngine(
                ex, tok, name="storechaos-jax", enable_metrics=False,
                max_decode_steps=12, clock=fclock, kv_pin_ttl=5.0,
                kv_tiering=tiering,
                async_pipeline=AsyncPipelineConfig(enabled=True,
                                                   depth=2))
            if store is not None and eng._tiering is not None:
                eng._tiering.store = store          # noqa: SLF001
            return eng, fclock

        prompts = {"j0": ("the quick brown fox", " jumps over"),
                   "j1": ("a slow green turtle", " crawls by")}

        # Baseline: pin-resident, no tiering, no store.
        eng, _ = build(None, None)
        base = {}
        for c, (p1, p2) in prompts.items():
            h1 = eng.submit(GenRequest(id=f"{c}.b1", prompt=p1,
                                       conversation_id=c,
                                       max_new_tokens=8))
            eng.run_until_idle()
            h2 = eng.submit(GenRequest(id=f"{c}.b2", prompt=p2,
                                       conversation_id=c,
                                       max_new_tokens=8))
            eng.run_until_idle()
            base[c] = (h1.result.tokens, h2.result.tokens)
        eng.stop()
        assert all(t1 and t2 for t1, t2 in base.values())

        # Chaos leg: tiering over a wrapped store, one conversation
        # forced to the store tier, blackout during its second turn.
        store = wrap_store(InMemoryStore(), _accept_rcfg(seed=12))
        checker = InvariantChecker()
        eng, fclock = build(
            KVTieringConfig(enabled=True, host_max_conversations=1,
                            store_spill=True), store)
        sm = StateManager(ConversationConfig(persist=True), store=store)
        eng.attach_conversation_manager(sm)
        plane = eng._tiering                        # noqa: SLF001
        try:
            out = {}
            for c, (p1, _) in prompts.items():
                # Warm turns pay one-time JAX compile; only the
                # blackout turns below hold the strict wall budget.
                h = _turn(eng, sm, checker, f"{c}.t1", c, p1,
                          budget_s=60.0)
                out[c] = [h.result.tokens]
            fclock.advance(6.0)
            eng.step()
            assert wait_until(
                lambda: sum(plane.counts().values()) == 2)
            # j0 demoted first → spilled to the store tier when j1's
            # demotion claimed the single host slot.
            assert store.totals["ops"] > 0

            _arm(72, {"point": "store.*", "kind": "error", "times": 200})
            for c, (_, p2) in prompts.items():
                h = _turn(eng, sm, checker, f"{c}.t2", c, p2)
                out[c].append(h.result.tokens)
            assert store.resilience_stats()["breaker"]["trips"] >= 1
            assert store.totals["errors"] > 0
            # Recompute-on-promote is CORRECT: token-for-token equal to
            # the pin-resident baseline even with the store dead.
            for c in prompts:
                assert (out[c][0], out[c][1]) == base[c], c

            chaos.configure(None)
            assert wait_until(lambda: not store.degraded, timeout=5.0)
            store.load("j0")        # probe success fires the recovery
            assert wait_until(lambda: sm.replay_pending() == 0,
                              timeout=5.0)

            # Store tier resumes: demote again against the healthy
            # store, and the next promote comes back as a STORE hit.
            fclock.advance(6.0)
            eng.step()
            assert wait_until(
                lambda: sum(plane.counts().values()) == 2)
            for c, (p1, _) in prompts.items():
                _turn(eng, sm, checker, f"{c}.t3", c, p1,
                      budget_s=60.0)
            assert eng.get_stats()["kv_tiering"]["hits"]["store"] >= 1
            checker.check()
        finally:
            eng.stop()
            store.close()
