"""Tenancy plane (llmq_tpu/tenancy/, docs/tenancy.md): weighted fair
dequeue, per-tenant quotas, burst isolation — and the hard off-switch.

The load-bearing contracts:

- WFQ converges to configured weights under saturation (echo engine and
  pure queue-level, both ordering backends);
- an idle tenant accumulates NO credit (virtual-time clamp on
  re-arrival);
- quota violations 429 with Retry-After at the overload seam;
- the in-flight cap DEFERS dispatch rather than rejecting work;
- ``tenancy.enabled: false`` dequeues token-for-token like
  FIFO-within-priority (and a single-tenant enabled system matches it);
- realtime beats batch regardless of tenant debt (priority × tenant);
- tenant_id survives WAL recovery and the spool round-trip.
"""

from __future__ import annotations

from types import SimpleNamespace

import pytest

from llmq_tpu.api.overload import OverloadShedder
from llmq_tpu.core.clock import FakeClock
from llmq_tpu.core.config import (Config, OverloadConfig,
                                  TenancyConfig, TenantClassConfig)
from llmq_tpu.core.errors import QueueEmptyError
from llmq_tpu.core.types import Message, Priority
from llmq_tpu.queueing.queue_manager import QueueManager
from llmq_tpu.tenancy import (FairScheduler, TenantRegistry,
                              configure_tenancy, estimate_tokens,
                              get_tenant_registry, reset_tenancy,
                              weighted_token_caps)


@pytest.fixture(autouse=True)
def _clean_tenancy():
    reset_tenancy()
    yield
    reset_tenancy()


def tenancy_cfg(enabled=True, tenants=None, **default_kw) -> Config:
    cfg = Config()
    cfg.queue.enable_metrics = False
    cfg.tenancy = TenancyConfig(
        enabled=enabled, tenants=tenants or {},
        default=TenantClassConfig(**default_kw))
    return cfg


def mk(mid, tenant="default", prio=Priority.NORMAL, content="x" * 40,
       **md) -> Message:
    m = Message(id=mid, content=content, priority=prio, tenant_id=tenant)
    m.metadata.update(md)
    return m


def drain_ids(mgr, queue="normal"):
    out = []
    while True:
        m = mgr.try_pop_message(queue)
        if m is None:
            return out
        out.append(m)
        mgr.complete_message(m)


# -- registry ------------------------------------------------------------------

class TestTenantRegistry:
    def test_spec_resolution_named_vs_default(self):
        reg = TenantRegistry()
        reg.configure(TenancyConfig(
            enabled=True, tenants={"acme": {"weight": 4.0,
                                            "max_inflight": 2}},
            default=TenantClassConfig(weight=1.0)))
        assert reg.enabled
        assert reg.spec_for("acme").weight == 4.0
        assert reg.spec_for("acme").max_inflight == 2
        assert reg.spec_for("anyone-else").weight == 1.0
        assert reg.weight_for("acme") == 4.0

    def test_invalid_weight_rejected(self):
        with pytest.raises(ValueError):
            TenantClassConfig(weight=0.0)

    def test_token_bucket_rate_and_refill(self):
        clock = FakeClock()
        reg = TenantRegistry(clock=clock)
        reg.configure(TenancyConfig(
            enabled=True,
            tenants={"t": {"token_rate": 100.0, "burst_tokens": 200.0}}))
        ok, _ = reg.admit_tokens("t", 200)       # drains the burst
        assert ok
        ok, retry = reg.admit_tokens("t", 100)
        assert not ok
        assert retry > 0
        clock.advance(1.0)                       # 100 tokens refill
        ok, _ = reg.admit_tokens("t", 100)
        assert ok

    def test_over_burst_request_admitted_as_debt(self):
        # A single request larger than the burst must not be stuck
        # forever: it is admitted against a full bucket and the excess
        # drains as debt at the sustained rate.
        clock = FakeClock()
        reg = TenantRegistry(clock=clock)
        reg.configure(TenancyConfig(
            enabled=True,
            tenants={"t": {"token_rate": 10.0, "burst_tokens": 50.0}}))
        ok, _ = reg.admit_tokens("t", 500)
        assert ok
        ok, retry = reg.admit_tokens("t", 1)
        assert not ok and retry > 0

    def test_unlimited_rate_always_admits(self):
        reg = TenantRegistry()
        reg.configure(TenancyConfig(enabled=True))
        for _ in range(1000):
            ok, _ = reg.admit_tokens("free", 10_000)
            assert ok

    def test_depth_and_inflight_counters(self):
        reg = TenantRegistry()
        reg.configure(TenancyConfig(
            enabled=True, tenants={"t": {"max_inflight": 1,
                                         "max_queue_depth": 2}}))
        reg.note_enqueued("t")
        reg.note_enqueued("t")
        assert reg.queue_depth("t") == 2
        assert reg.over_queue_depth("t")
        reg.note_dequeued("t")
        assert not reg.over_queue_depth("t")
        assert not reg.at_inflight_cap("t")
        reg.acquire_inflight("t")
        assert reg.at_inflight_cap("t")
        reg.release_inflight("t")
        assert not reg.at_inflight_cap("t")
        # Counters never go negative.
        reg.note_dequeued("t")
        reg.note_dequeued("t")
        assert reg.queue_depth("t") == 0
        reg.release_inflight("t")
        assert reg.inflight("t") == 0

    def test_bucket_lru_never_evicts_configured_tenant(self):
        clock = FakeClock()
        reg = TenantRegistry(clock=clock)
        reg.MAX_TRACKED = 8
        reg.configure(TenancyConfig(
            enabled=True, tenants={"vip": {"token_rate": 1000.0}},
            default=TenantClassConfig(token_rate=1000.0)))
        reg.admit_tokens("vip", 500)     # vip's bucket is half-drained
        for i in range(50):              # id spray
            reg.admit_tokens(f"spray-{i}", 1)
        ok, _ = reg.admit_tokens("vip", 400)   # still remembers level
        assert ok
        ok, _ = reg.admit_tokens("vip", 400)   # would need a refill
        assert not ok

    def test_estimate_tokens(self):
        assert estimate_tokens(mk("a", content="x" * 400)) == 100 + 64
        assert estimate_tokens(
            mk("b", content="x" * 40, max_new_tokens=10)) == 10 + 10
        assert estimate_tokens(mk("c", content="")) >= 1


# -- weighted caps helper (engine-level fairness) ------------------------------

class TestWeightedTokenCaps:
    def test_proportional_split(self):
        caps = weighted_token_caps({"a": 4.0, "b": 1.0}, 100)
        assert caps["a"] == 80 and caps["b"] == 20

    def test_rounding_conserves_total(self):
        caps = weighted_token_caps({"a": 1, "b": 1, "c": 1}, 100)
        assert sum(caps.values()) == 100
        assert all(v >= 33 for v in caps.values())

    def test_every_tenant_gets_at_least_one(self):
        caps = weighted_token_caps({"a": 1000.0, "b": 0.001}, 10)
        assert caps["b"] >= 1

    def test_empty_and_zero(self):
        assert weighted_token_caps({}, 100) == {}
        assert weighted_token_caps({"a": 1.0}, 0) == {"a": 0}


# -- fair dequeue over the queue plane ----------------------------------------

class TestFairDequeue:
    def test_weighted_interleave_4_to_1(self, queue_backend):
        cfg = tenancy_cfg(tenants={"a": {"weight": 4.0},
                                   "b": {"weight": 1.0}})
        mgr = QueueManager("wfq", config=cfg, backend=queue_backend)
        for i in range(40):
            mgr.push_message(mk(f"a{i}", "a"))
            mgr.push_message(mk(f"b{i}", "b"))
        order = [m.tenant_id for m in drain_ids(mgr)]
        # While both tenants are backlogged, every window of service
        # gives a ~4x the tokens (equal-size requests → 4x the pops).
        head = order[:25]
        n_a, n_b = head.count("a"), head.count("b")
        assert n_b > 0
        assert 2.5 <= n_a / n_b <= 6.0, order[:25]
        mgr.stop()

    def test_fifo_within_tenant(self, queue_backend):
        cfg = tenancy_cfg(tenants={"a": {"weight": 2.0}})
        mgr = QueueManager("fifo", config=cfg, backend=queue_backend)
        for i in range(10):
            mgr.push_message(mk(f"a{i}", "a"))
        ids = [m.id for m in drain_ids(mgr)]
        assert ids == [f"a{i}" for i in range(10)]
        mgr.stop()

    def test_single_tenant_matches_disabled_order(self, queue_backend):
        msgs = [(f"m{i}", Priority.NORMAL if i % 3 else Priority.HIGH)
                for i in range(30)]
        orders = []
        for enabled in (False, True):
            cfg = tenancy_cfg(enabled=enabled)
            mgr = QueueManager(f"eq-{enabled}", config=cfg,
                               backend=queue_backend)
            for mid, prio in msgs:
                mgr.push_message(mk(mid, "default", prio))
            got = []
            for q in ("high", "normal"):
                got.extend(m.id for m in drain_ids(mgr, q))
            orders.append(got)
            mgr.stop()
        assert orders[0] == orders[1]

    def test_off_switch_is_plain_fifo_within_priority(self,
                                                      queue_backend):
        """tenancy.enabled=false: multi-tenant pushes dequeue in exact
        arrival order within each tier — the pre-tenancy contract,
        token-for-token."""
        cfg = tenancy_cfg(enabled=False,
                          tenants={"a": {"weight": 100.0}})
        mgr = QueueManager("off", config=cfg, backend=queue_backend)
        assert mgr._fair is None                       # noqa: SLF001
        assert mgr.queue._fair is None                 # noqa: SLF001
        expected = []
        for i in range(30):
            tenant = ["a", "b", "c"][i % 3]
            mgr.push_message(mk(f"m{i}", tenant))
            expected.append(f"m{i}")
        assert [m.id for m in drain_ids(mgr)] == expected
        mgr.stop()

    def test_idle_tenant_hoards_no_credit(self, queue_backend):
        """Tenant b sits out while a is served heavily; on re-arrival b
        gets its fair share — NOT a monopoly amortizing the idle time."""
        cfg = tenancy_cfg(tenants={"a": {"weight": 1.0},
                                   "b": {"weight": 1.0}})
        mgr = QueueManager("idle", config=cfg, backend=queue_backend)
        # Phase 1: only a is backlogged; 40 pops all go to a.
        for i in range(60):
            mgr.push_message(mk(f"a{i}", "a"))
        for _ in range(40):
            m = mgr.pop_message("normal")
            assert m.tenant_id == "a"
            mgr.complete_message(m)
        # Phase 2: b arrives from idle. With hoarded credit b would own
        # the next ~40 pops; with the clamp service is ~50/50.
        for i in range(60):
            mgr.push_message(mk(f"b{i}", "b"))
        head = []
        for _ in range(20):
            m = mgr.pop_message("normal")
            head.append(m.tenant_id)
            mgr.complete_message(m)
        n_b = head.count("b")
        assert 6 <= n_b <= 14, head
        mgr.stop()

    def test_priority_beats_tenant_debt(self, queue_backend):
        """A deeply indebted tenant's REALTIME request is still served
        before any other tenant's NORMAL work: WFQ reorders only within
        a level, never across levels."""
        cfg = tenancy_cfg(tenants={"heavy": {"weight": 1.0},
                                   "light": {"weight": 100.0}})
        mgr = QueueManager("prio", config=cfg, backend=queue_backend)
        for i in range(20):                  # build heavy's debt
            mgr.push_message(mk(f"h{i}", "heavy"))
            m = mgr.pop_message("normal")
            mgr.complete_message(m)
        mgr.push_message(mk("light-normal", "light"))
        mgr.push_message(mk("heavy-rt", "heavy", Priority.REALTIME))
        batch = mgr.drain_in_priority_order(10)
        assert [m.id for m in batch] == ["heavy-rt", "light-normal"]
        mgr.stop()

    def test_inflight_cap_defers_not_rejects(self, queue_backend):
        cfg = tenancy_cfg(tenants={"capped": {"max_inflight": 1}})
        mgr = QueueManager("cap", config=cfg, backend=queue_backend)
        mgr.push_message(mk("c1", "capped"))
        mgr.push_message(mk("c2", "capped"))
        m1 = mgr.pop_message("normal")
        assert m1.id == "c1"
        # c2 is deferred while c1 is in flight — reads as empty.
        assert mgr.try_pop_message("normal") is None
        assert mgr.total_pending() == 1      # ... but not lost
        # Repeated polls mint NO additional deferral events: one per
        # held-back handle, not per worker poll (else the counter
        # measures poll cadence, not deferred work).
        for _ in range(20):
            assert mgr.try_pop_message("normal") is None
        reg = get_tenant_registry()
        assert reg.rejections_total.get("inflight", 0) == 1
        mgr.complete_message(m1)
        m2 = mgr.pop_message("normal")
        assert m2.id == "c2"
        mgr.complete_message(m2)
        mgr.stop()

    def test_inflight_cap_released_on_failure_and_requeue(
            self, queue_backend):
        cfg = tenancy_cfg(tenants={"t": {"max_inflight": 1}})
        mgr = QueueManager("fcap", config=cfg, backend=queue_backend)
        mgr.push_message(mk("f1", "t"))
        mgr.push_message(mk("f2", "t"))
        m1 = mgr.pop_message("normal")
        mgr.fail_message(m1)
        m2 = mgr.pop_message("normal")
        assert m2.id == "f2"
        mgr.complete_message(m2)
        # Retry stash also releases.
        mgr.push_message(mk("f3", "t"))
        m3 = mgr.pop_message("normal")
        mgr.stash_for_retry(m3)
        mgr.push_message(mk("f4", "t"))
        assert mgr.pop_message("normal").id == "f4"
        mgr.stop()

    def test_other_tenant_unaffected_by_cap(self, queue_backend):
        cfg = tenancy_cfg(tenants={"capped": {"max_inflight": 1}})
        mgr = QueueManager("cap2", config=cfg, backend=queue_backend)
        mgr.push_message(mk("c1", "capped"))
        mgr.push_message(mk("c2", "capped"))
        mgr.push_message(mk("free1", "free"))
        got1 = mgr.pop_message("normal")
        got2 = mgr.pop_message("normal")
        assert {got1.id, got2.id} == {"c1", "free1"}
        assert mgr.try_pop_message("normal") is None   # c2 deferred
        mgr.stop()

    def test_share_window_ages_out_on_the_manager_clock(self):
        """share_ratios uses the scheduler's injected clock, so the
        rolling window really expires (and fake-clock tests really
        test it)."""
        cfg = tenancy_cfg(tenants={"a": {"weight": 1.0}})
        clock = FakeClock()
        reg = configure_tenancy(cfg.tenancy)
        fair = FairScheduler(reg, clock=clock)
        msg = mk("s1", "a")
        fair.note_pop(msg)
        msg.metadata["usage"] = {"prompt_tokens": 5,
                                 "completion_tokens": 5}
        fair.note_finish(msg)
        assert fair.share_ratios() == {"a": 1.0}
        clock.advance(reg.share_window_s + 1.0)
        assert fair.share_ratios() == {}

    def test_admin_remove_keeps_fair_accounting(self, queue_backend):
        cfg = tenancy_cfg()
        mgr = QueueManager("adm", config=cfg, backend=queue_backend)
        mgr.push_message(mk("r1", "t"))
        mgr.push_message(mk("r2", "t"))
        assert mgr.remove_message("r1") is not None
        reg = get_tenant_registry()
        assert reg.queue_depth("t") == 1
        assert mgr.pop_message("normal").id == "r2"
        assert reg.queue_depth("t") == 0
        mgr.stop()

    def test_expired_messages_drop_from_fair_index(self, queue_backend,
                                                   fake_clock):
        cfg = tenancy_cfg()
        cfg.queue.stale_message_age = 10.0
        mgr = QueueManager("exp", config=cfg, clock=fake_clock,
                           backend=queue_backend)
        mgr.push_message(mk("old", "t"))
        fake_clock.advance(60.0)
        mgr.push_message(mk("new", "t"))
        mgr.run_monitor_once()               # expires "old"
        # Expired work leaves the quota depth counter IMMEDIATELY —
        # dead messages must not hold a tenant at its max_queue_depth
        # cap (they might never surface while the tenant is deferred).
        assert get_tenant_registry().queue_depth("t") == 1
        assert mgr.pop_message("normal").id == "new"
        assert get_tenant_registry().queue_depth("t") == 0
        with pytest.raises(QueueEmptyError):
            mgr.pop_message("normal")
        mgr.stop()

    def test_capped_tenant_does_not_pin_virtual_floor(self):
        """A tenant deferred at its in-flight cap has a frozen vt; it
        must not pin the virtual floor, or a newly-arriving tenant
        clamps far below the actively-served ones and starves them."""
        cfg = tenancy_cfg(tenants={"a": {"max_inflight": 1}})
        reg = configure_tenancy(cfg.tenancy)
        fair = FairScheduler(reg)
        msgs, handles = {}, iter(range(1000))

        def push(mid, tenant):
            m, h = mk(mid, tenant), next(handles)
            msgs[h] = m
            fair.on_push("normal", m, h)

        def serve():
            h = fair.select("normal")
            assert h is not None
            fair.note_pop(msgs[h])
            return msgs[h]

        push("a1", "a")
        assert serve().id == "a1"     # a is now at its in-flight cap
        push("a2", "a")               # deferred; vt_a frozen low
        for i in range(6):
            push(f"b{i}", "b")
        for _ in range(6):
            assert serve().tenant_id == "b"
        push("c1", "c")               # arrives from idle
        vt = fair.virtual_times()
        assert vt["c"] > vt["a"]      # clamped to live service, not
        assert vt["c"] >= vt["b"] - 80   # to a's frozen counter

    def test_true_up_from_measured_usage(self, queue_backend):
        """A tenant whose requests turn out much LARGER than estimated
        falls further behind after the finish-time true-up."""
        cfg = tenancy_cfg(tenants={"a": {"weight": 1.0},
                                   "b": {"weight": 1.0}})
        mgr = QueueManager("tu", config=cfg, backend=queue_backend)
        fair = mgr._fair                      # noqa: SLF001
        for i in range(4):
            mgr.push_message(mk(f"a{i}", "a"))
        m = mgr.pop_message("normal")
        # The engine measured 100x the estimate.
        m.metadata["usage"] = {"prompt_tokens": 5000,
                               "completion_tokens": 5000}
        mgr.complete_message(m)
        vt = fair.virtual_times()
        assert vt["a"] > 9000                 # est ~74 → trued up to 10k
        mgr.stop()


# -- quota 429 at the overload seam -------------------------------------------

class TestQuota429:
    def _shedder(self, tenants, **default_kw):
        cfg = Config()
        cfg.tenancy = TenancyConfig(
            enabled=True, tenants=tenants,
            default=TenantClassConfig(**default_kw))
        reg = configure_tenancy(cfg.tenancy)
        return OverloadShedder(OverloadConfig(), cfg.queue,
                               tenant_registry=reg,
                               enable_metrics=False), reg

    def test_rate_limit_429_with_retry_after(self):
        from llmq_tpu.api.server import ApiError
        shedder, reg = self._shedder(
            {"t": {"token_rate": 50.0, "burst_tokens": 100.0}})
        msg = mk("q1", "t", content="x" * 400)     # ~164 est tokens
        # The first over-burst request is admitted against the full
        # bucket as debt (it could never wait its way in); the SECOND
        # hits the drained bucket and sheds with a rate-derived
        # Retry-After.
        shedder.admit(msg, None, 0.0)
        with pytest.raises(ApiError) as ei:
            shedder.admit(mk("q1b", "t", content="x" * 400), None, 0.0)
        assert ei.value.status == 429
        assert ei.value.retry_after is not None
        assert ei.value.retry_after > 0
        assert "tenant_quota" in ei.value.message
        assert shedder.shed_counts["tenant_quota"] == 1
        assert reg.rejections_total.get("rate") == 1

    def test_global_shed_does_not_drain_bucket(self):
        """A request shed by a GLOBAL check (backlog) must not consume
        its tenant's token bucket — the rate gate peeks before the
        global gates and charges only on admission, so a backlog
        episode can't starve the tenant's quota for work that was
        never served."""
        from llmq_tpu.api.server import ApiError
        shedder, reg = self._shedder(
            {"t": {"token_rate": 50.0, "burst_tokens": 100.0}})
        shedder.queue_depth_limit = 1
        backlogged = SimpleNamespace(total_pending=lambda: 50)
        for i in range(5):            # 5 × ~41 est tokens ≫ the burst
            with pytest.raises(ApiError) as ei:
                shedder.admit(mk(f"g{i}", "t", content="x" * 100),
                              backlogged, 0.0)
            assert "backlog" in ei.value.message
        ok, _ = reg.admit_tokens("t", 100, consume=False)
        assert ok                     # bucket still holds the full burst
        assert shedder.shed_counts["tenant_quota"] == 0

    def test_quota_enforced_when_overload_disabled(self):
        """Tenant quotas ride the shedding seam but must not depend on
        ``overload.enabled`` — build_shedder hands back a shedder with
        every GLOBAL check neutralized when only tenancy is on."""
        from llmq_tpu.api.overload import build_shedder
        from llmq_tpu.api.server import ApiError
        cfg = Config()
        cfg.overload.enabled = False
        cfg.queue.enable_metrics = False
        cfg.tenancy = TenancyConfig(
            enabled=True,
            tenants={"t": {"token_rate": 10.0, "burst_tokens": 20.0}})
        shedder = build_shedder(cfg)
        assert shedder is not None
        # Global backlog shedding really is off ...
        deep = SimpleNamespace(total_pending=lambda: 10**6)
        shedder.admit(mk("ok", "quiet"), deep, 0.0)
        # ... while the tenant rate gate still enforces.
        shedder.admit(mk("d1", "t", content="x" * 100), None, 0.0)
        with pytest.raises(ApiError) as ei:
            shedder.admit(mk("d2", "t", content="x" * 100), None, 0.0)
        assert ei.value.status == 429
        assert "tenant_quota" in ei.value.message

    def test_queue_depth_429(self):
        from llmq_tpu.api.server import ApiError
        shedder, reg = self._shedder({"t": {"max_queue_depth": 2}})
        reg.note_enqueued("t")
        reg.note_enqueued("t")
        with pytest.raises(ApiError) as ei:
            shedder.admit(mk("q2", "t"), None, 0.0)
        assert ei.value.status == 429
        assert reg.rejections_total.get("queue_depth") == 1

    def test_other_tenants_unaffected(self):
        shedder, _ = self._shedder(
            {"noisy": {"token_rate": 1.0, "burst_tokens": 1.0}})
        shedder.admit(mk("ok", "quiet"), None, 0.0)   # no raise

    def test_disabled_registry_is_inert(self):
        cfg = Config()
        reg = configure_tenancy(cfg.tenancy)   # enabled=False
        shedder = OverloadShedder(OverloadConfig(), cfg.queue,
                                  tenant_registry=reg,
                                  enable_metrics=False)
        shedder.admit(mk("any", "t"), None, 0.0)

    def test_end_to_end_429_through_api(self):
        """The full submit path: POST with X-Tenant-Id over the rate
        limit → 429 body carries retry_after."""
        import json as _json
        from llmq_tpu.api.server import ApiServer
        from llmq_tpu.queueing.factory import QueueFactory, QueueType
        cfg = tenancy_cfg(
            tenants={"noisy": {"token_rate": 10.0, "burst_tokens": 80.0}})
        cfg.queue.enable_metrics = False
        factory = QueueFactory(cfg)
        factory.create_queue_manager("standard", QueueType.STANDARD,
                                     start_background=False)
        api = ApiServer(cfg, queue_factory=factory)
        body = _json.dumps({"content": "y" * 400,
                            "tenant_id": "noisy"}).encode()
        status1, _, _ = api.dispatch("POST", "/api/v1/messages", body)
        assert status1 == 202
        status2, payload, _ = api.dispatch("POST", "/api/v1/messages",
                                           body)
        assert status2 == 429
        assert payload["retry_after"] > 0
        assert "tenant_quota" in payload["error"]
        # The tenancy introspection route sees the rejection.
        status3, snap, _ = api.dispatch("GET", "/api/v1/tenancy", b"")
        assert status3 == 200
        assert snap["rejections"].get("rate") == 1
        factory.stop_all()


# -- engine-level decode fairness ---------------------------------------------

class TestEngineDecodeFairness:
    def _engine(self):
        from llmq_tpu.engine.engine import InferenceEngine
        from llmq_tpu.engine.executor import EchoExecutor
        from llmq_tpu.engine.tokenizer import ByteTokenizer
        tok = ByteTokenizer()
        ex = EchoExecutor(batch_size=4, page_size=8, num_pages=256,
                          max_pages_per_seq=16, eos_id=tok.eos_id,
                          chunk_size=8)
        return InferenceEngine(ex, tok, name="tenancy-echo",
                               enable_metrics=False, max_decode_steps=32)

    def _rows(self, spec):
        # (tenant, budget) → minimal row objects for the cap pass.
        rows, budgets = [], {}
        for i, (tenant, budget) in enumerate(spec):
            rows.append(SimpleNamespace(
                slot=i, order=i, req=SimpleNamespace(tenant_id=tenant)))
            budgets[i] = budget
        return rows, budgets

    def test_caps_bind_only_under_contention(self):
        configure_tenancy(TenancyConfig(
            enabled=True, tenants={"a": {"weight": 1.0},
                                   "b": {"weight": 1.0}}))
        eng = self._engine()
        # Single tenant: untouched even with wildly uneven budgets.
        rows, budgets = self._rows([("a", 8), ("a", 8), ("a", 8)])
        before = dict(budgets)
        eng._apply_decode_fairness(rows, budgets)      # noqa: SLF001
        assert budgets == before
        # Two equal-weight tenants, a hogging 3 of 4 rows: a's rows are
        # scaled toward a 50% token share; b keeps its full budget.
        rows, budgets = self._rows(
            [("a", 8), ("a", 8), ("a", 8), ("b", 8)])
        eng._apply_decode_fairness(rows, budgets)      # noqa: SLF001
        a_sum = budgets[0] + budgets[1] + budgets[2]
        assert budgets[3] == 8
        assert a_sum <= 16                             # 50% of 32
        eng.stop()

    def test_weighted_cap_respects_weights(self):
        configure_tenancy(TenancyConfig(
            enabled=True, tenants={"a": {"weight": 3.0},
                                   "b": {"weight": 1.0}}))
        eng = self._engine()
        rows, budgets = self._rows(
            [("a", 8), ("a", 8), ("b", 8), ("b", 8)])
        eng._apply_decode_fairness(rows, budgets)      # noqa: SLF001
        a_sum = budgets[0] + budgets[1]
        b_sum = budgets[2] + budgets[3]
        assert a_sum == 16                 # under its 24-token share
        assert b_sum <= 8                  # capped at 25% of 32
        eng.stop()

    def test_budget_never_drops_to_zero(self):
        configure_tenancy(TenancyConfig(
            enabled=True, tenants={"a": {"weight": 1.0},
                                   "b": {"weight": 1000.0}}))
        eng = self._engine()
        rows, budgets = self._rows([("a", 8), ("a", 8), ("b", 8)])
        eng._apply_decode_fairness(rows, budgets)      # noqa: SLF001
        assert budgets[0] >= 1 and budgets[1] >= 1
        eng.stop()

    @staticmethod
    def _cand(order, tenant, todo):
        return SimpleNamespace(
            order=order, req=SimpleNamespace(tenant_id=tenant),
            todo_ids=list(range(todo)))

    def test_prefill_leftover_pass_widens_capped_slice(self):
        """Work conservation: when one tenant can't use its share of
        the prefill budget, the leftover pass WIDENS the other
        tenant's pass-1-truncated slice instead of stranding budget."""
        from llmq_tpu.engine.engine import _pack_prefill_slices
        cands = [self._cand(0, "a", 10), self._cand(1, "b", 800)]
        plan = _pack_prefill_slices(cands, 4, 512, 512,
                                    {"a": 256, "b": 256})
        got = {s.req.tenant_id: len(sl) for s, sl in plan}
        # a takes its 10; b is capped at 256 in pass 1, then widened
        # with the 246 a left unclaimed — the full 512 budget packs.
        assert got == {"a": 10, "b": 502}

    def test_prefill_caps_bind_under_real_contention(self):
        """Both tenants saturating: equal caps split the budget and the
        leftover pass has nothing to hand out."""
        from llmq_tpu.engine.engine import _pack_prefill_slices
        cands = [self._cand(0, "a", 800), self._cand(1, "b", 800)]
        plan = _pack_prefill_slices(cands, 4, 512, 512,
                                    {"a": 256, "b": 256})
        got = {s.req.tenant_id: len(sl) for s, sl in plan}
        assert got == {"a": 256, "b": 256}

    def test_prefill_uncapped_pack_matches_greedy(self):
        """No caps (tenancy off / one tenant): plain urgency-order
        greedy pack, honoring S, T and the budget."""
        from llmq_tpu.engine.engine import _pack_prefill_slices
        cands = [self._cand(i, "a", 300) for i in range(4)]
        plan = _pack_prefill_slices(cands, 2, 256, 400, None)
        assert [(s.order, len(sl)) for s, sl in plan] == [(0, 256),
                                                          (1, 144)]

    def test_two_tenant_echo_decode_equivalence_off(self):
        """With tenancy DISABLED, a two-tenant echo run produces the
        same outputs as always — the fused-step gate really is one
        attribute check (off-switch at the engine layer)."""
        from llmq_tpu.engine.engine import GenRequest
        eng = self._engine()
        assert not eng._tenancy.enabled                # noqa: SLF001
        eng.start()
        try:
            handles = [eng.submit(GenRequest(
                id=f"r{i}", prompt=f"hi {i}", max_new_tokens=6,
                tenant_id="a" if i % 2 else "b")) for i in range(6)]
            for h in handles:
                assert h.wait(10.0)
                assert h.result.finish_reason in ("eos", "length")
        finally:
            eng.stop()


# -- WFQ convergence through the full echo stack -------------------------------

class TestConvergenceEcho:
    def test_token_share_converges_to_weights(self, queue_backend):
        """Saturated two-tenant drain through manager + echo engine
        process_fn: within the contended window, served tokens split
        ~4:1 (the ISSUE acceptance shape, queue-level)."""
        cfg = tenancy_cfg(tenants={"a": {"weight": 4.0},
                                   "b": {"weight": 1.0}})
        mgr = QueueManager("conv", config=cfg, backend=queue_backend)
        fair = mgr._fair                       # noqa: SLF001
        from llmq_tpu.engine.engine import InferenceEngine
        from llmq_tpu.engine.executor import EchoExecutor
        from llmq_tpu.engine.tokenizer import ByteTokenizer
        tok = ByteTokenizer()
        ex = EchoExecutor(batch_size=8, page_size=8, num_pages=512,
                          max_pages_per_seq=16, eos_id=tok.eos_id,
                          chunk_size=8)
        eng = InferenceEngine(ex, tok, name="conv-echo",
                              enable_metrics=False, max_decode_steps=16)
        eng.start()
        try:
            n = 60
            for i in range(n):
                mgr.push_message(mk(f"a{i}", "a", content="hello a",
                                    max_new_tokens=8))
                mgr.push_message(mk(f"b{i}", "b", content="hello b",
                                    max_new_tokens=8))
            # Serve only the first half of the offered load, so the
            # measurement window is fully contended (both backlogged).
            served = 0
            while served < n:
                m = mgr.try_pop_message("normal")
                if m is None:
                    break
                eng.process_fn(None, m)
                mgr.complete_message(m)
                served += 1
            tokens = fair.served_tokens
            ratio = tokens.get("a", 0) / max(1, tokens.get("b", 0))
            assert 4 * 0.6 <= ratio <= 4 * 1.6, tokens
        finally:
            eng.stop()
            mgr.stop()


# -- durability ---------------------------------------------------------------

class TestTenantDurability:
    def test_tenant_survives_wal_recovery_with_fairness(self, tmp_path):
        wal = str(tmp_path / "tenancy.wal")
        cfg = tenancy_cfg(tenants={"a": {"weight": 4.0},
                                   "b": {"weight": 1.0}})
        cfg.queue.wal_dir = str(tmp_path)
        mgr = QueueManager("wal", config=cfg, wal_path=wal)
        for i in range(10):
            mgr.push_message(mk(f"a{i}", "a"))
            mgr.push_message(mk(f"b{i}", "b"))
        mgr.stop()
        # Crash-recover into a FRESH manager: attribution is kept and
        # the restored messages re-enter the fair index (the dequeue
        # is weighted, not the WAL's FIFO replay order).
        mgr2 = QueueManager("wal", config=cfg, wal_path=wal)
        out = drain_ids(mgr2)
        assert len(out) == 20
        assert {m.tenant_id for m in out} == {"a", "b"}
        head = [m.tenant_id for m in out[:10]]
        assert head.count("a") > head.count("b"), head
        mgr2.stop()

    def test_tenant_survives_spool_roundtrip(self, tmp_path):
        from llmq_tpu.queueing.spool import SpoolConsumer, SpoolProducer
        sd = str(tmp_path / "spool")
        prod = SpoolProducer(sd)
        prod.push(mk("s1", "acme-corp"), queue_name="normal")
        got = []
        consumer = SpoolConsumer(
            sd, lambda q, m: got.append((q, m)))
        consumer.run_once()
        assert len(got) == 1
        assert got[0][1].tenant_id == "acme-corp"
        assert got[0][1].id == "s1"


# -- metrics ------------------------------------------------------------------

class TestTenancyMetrics:
    def test_families_flush_with_bounded_labels(self):
        from llmq_tpu.metrics.registry import exposition
        from llmq_tpu.observability.usage import reset_usage
        reset_usage()
        cfg = tenancy_cfg(tenants={"acme": {"weight": 4.0,
                                            "max_inflight": 1}})
        mgr = QueueManager("met", config=cfg, backend="python")
        mgr.push_message(mk("m1", "acme", max_new_tokens=16))
        mgr.push_message(mk("m2", "acme"))
        m = mgr.pop_message("normal")
        assert mgr.try_pop_message("normal") is None   # inflight defer
        m.metadata["usage"] = {"prompt_tokens": 10,
                               "completion_tokens": 16}
        mgr.complete_message(m)
        exp = exposition().decode()
        assert 'tenant_inflight{tenant="acme"}' in exp
        assert 'tenant_virtual_time{tenant="acme"}' in exp
        assert 'tenant_share_ratio{tenant="acme"}' in exp
        assert ('tenant_quota_rejections_total{reason="inflight"}'
                in exp)
        mgr.stop()

    def test_departed_tenant_series_removed(self):
        """An unconfigured tenant's gauges must disappear when it
        leaves, not freeze at the last flushed value forever."""
        from llmq_tpu.metrics.registry import exposition
        from llmq_tpu.observability.usage import reset_usage
        reset_usage()
        cfg = tenancy_cfg(tenants={"acme": {"weight": 4.0}})
        mgr = QueueManager("gone", config=cfg, backend="python")
        mgr.push_message(mk("departed-1", "transient"))
        m = mgr.pop_message("normal")
        exp = exposition().decode()
        assert 'tenant_inflight{tenant="transient"} 1.0' in exp
        mgr.complete_message(m)
        exp = exposition().decode()
        assert 'tenant_inflight{tenant="transient"}' not in exp
        assert 'tenant_inflight{tenant="acme"} 0.0' in exp
        mgr.stop()

    def test_id_shaped_tenant_never_mints_series(self):
        from llmq_tpu.metrics.registry import exposition
        from llmq_tpu.observability.usage import reset_usage
        reset_usage()
        sprayed = "0123456789abcdef0123456789abcdef"
        cfg = tenancy_cfg()
        mgr = QueueManager("spray", config=cfg, backend="python")
        mgr.push_message(mk("sp1", sprayed))
        m = mgr.pop_message("normal")
        mgr.complete_message(m)
        exp = exposition().decode()
        assert sprayed not in exp
        mgr.stop()

    def test_queue_stats_unaffected_by_fair_pops(self, queue_backend):
        """Fair pops keep the core's pending/processing/wait accounting
        moving exactly like plain pops."""
        cfg = tenancy_cfg(tenants={"a": {"weight": 4.0}})
        mgr = QueueManager("acct", config=cfg, backend=queue_backend)
        for i in range(6):
            mgr.push_message(mk(f"a{i}", "a"))
            mgr.push_message(mk(f"b{i}", "b"))
        for _ in range(8):
            m = mgr.pop_message("normal")
            mgr.complete_message(m)
        s = mgr.get_stats("normal")
        assert s.pending_count == 4
        assert s.processing_count == 0
        assert s.completed_count == 8
        assert s.wait_samples == 8
        mgr.stop()
