"""Conversation-residency soak (ISSUE satellite): StateManager +
tiered KV plane accounting stays CONSERVED under deep conversation
churn — every conversation ever created is either live in memory or
was evicted exactly once (hooks fire once, never twice, never for a
live id), the global residency cap holds at every checkpoint, and the
tiering plane's host/store entry counts never exceed their bounds or
lose track of a demoted conversation.

FakeClock-compressed: hours of idle-expiry churn run in seconds. The
tier-1 variant soaks 10^3 conversations; the ``slow`` variant is the
10^5 bar backing the million-user residency claim (PAPER.md) at the
state-plane layer — the closed-loop engine equivalent lives in
tests/test_scenarios.py::TestFullScaleSoak.
"""

from __future__ import annotations

import numpy as np
import pytest

from llmq_tpu.core.clock import FakeClock
from llmq_tpu.core.config import ConversationConfig, KVTieringConfig
from llmq_tpu.core.types import Message
from llmq_tpu.conversation import InMemoryStore, StateManager
from llmq_tpu.tiering import KVTieringPlane


class _TinyKVExec:
    """Minimal export/import surface so the plane carries real (small)
    page payloads — one 64-float page per conversation."""

    def kv_page_spec(self):
        return [((16,), np.dtype(np.float32))]

    def export_kv_pages(self, pages):
        return [np.stack([np.full((16,), float(p), np.float32)
                          for p in pages], axis=0)]

    def import_kv_pages(self, pages, leaves):
        pass


def _drain(plane, timeout=30.0):
    """Wait for the plane's worker queue to go idle."""
    import time
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if plane._q.qsize() == 0:  # noqa: SLF001 — test-only idle probe
            return True
        time.sleep(0.002)
    return False


def _residency_soak(n: int, *, live_cap: int = 512,
                    host_cap: int = 64) -> None:
    clock = FakeClock()
    cfg = ConversationConfig(max_conversations=live_cap,
                             max_conversations_per_user=10_000,
                             max_idle_time=600.0, ttl=0.0,
                             cleanup_interval=0.0, persist=True)
    sm = StateManager(cfg, store=InMemoryStore(), clock=clock)
    plane = KVTieringPlane(
        KVTieringConfig(enabled=True, host_capacity_mb=1,
                        host_max_conversations=host_cap),
        "soak", _TinyKVExec(), clock=clock, metrics=False)
    plane.store = InMemoryStore()

    evicted: list = []
    # Mirror the engine wiring: a conversation expiring out of the
    # state plane drops its tiered KV in the same motion.
    sm.on_evict(lambda c: (evicted.append(c.id), plane.forget(c.id)))

    demoted = 0
    for i in range(n):
        cid = f"soak-c{i}"
        sm.add_message(cid, Message(content="turn payload " + cid,
                                    user_id=f"u{i % 97}"))
        if i % 3 == 0:
            # A third of the conversations park KV in the tier plane
            # (page id bounded so payloads stay tiny).
            plane.demote(cid, [i % 29], [1, 2, 3, 4], 4, None)
            demoted += 1
        if i % 257 == 0:
            clock.advance(30.0)
            sm.run_cleanup_once()
            # Conservation at every checkpoint, not just at the end.
            assert sm.count() <= live_cap
            assert sm.count() + len(evicted) == i + 1

    # Conservation over the whole run: exactly-once eviction, no
    # overlap between live and evicted, nothing lost.
    assert len(evicted) == len(set(evicted)), "a conversation evicted twice"
    evicted_set = set(evicted)
    live = {f"soak-c{i}" for i in range(n)} - evicted_set
    assert sm.count() == len(live)
    for cid in list(live)[:50]:
        assert sm.get_or_create(cid).id == cid

    # Tier plane: bounded host residency, every demoted conversation
    # either still tracked (host or store) or forgotten via the evict
    # hook — never double-counted, never leaked past its bound.
    assert _drain(plane), "tiering worker wedged"
    counts = plane.counts()
    assert counts["host"] <= host_cap
    assert counts["host"] + counts["store"] <= demoted
    st = plane.stats()
    assert st["demotions"] == demoted
    # Store entries only ever arrive via a spill (spills is monotone;
    # forget() can shrink the store count but never grow it).
    assert st["spills"] >= counts["store"]

    # Final drain: everything idles out; the state plane empties and
    # the ledger of evictions accounts for every conversation created.
    clock.advance(3600.0)
    sm.run_cleanup_once()
    assert sm.count() == 0
    assert len(evicted) == n
    assert set(evicted) == {f"soak-c{i}" for i in range(n)}
    plane.stop()


class TestResidencySoak:
    def test_residency_conservation_1k(self):
        _residency_soak(1_000)

    @pytest.mark.slow
    def test_residency_conservation_100k(self):
        _residency_soak(100_000)
