"""Remote-engine HTTP transport (BASELINE config #5's dispatch half).

The reference fabricates worker URLs and never dispatches to them
(scheduler.go:299-301; SURVEY §3.5). These tests prove this framework's
transport is real: a gateway LoadBalancer routes drained messages over
HTTP to peer serve processes — with session affinity, EWMA feedback,
and failover through the health state machine when a peer's engine
dies. The last test runs two genuine OS processes (``python -m
llmq_tpu serve``) behind one gateway router and kills one.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from llmq_tpu.api.server import ApiServer
from llmq_tpu.core.config import LoadBalancerConfig, default_config
from llmq_tpu.core.types import Message, Priority
from llmq_tpu.engine import ByteTokenizer, EchoExecutor, InferenceEngine
from llmq_tpu.loadbalancer import (EngineRouter, HttpEngineClient,
                                   LoadBalancer)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _engine() -> InferenceEngine:
    eng = InferenceEngine(EchoExecutor(batch_size=4), ByteTokenizer(),
                          enable_metrics=False)
    eng.start()
    return eng


def _serve_pair():
    """Two in-process engines, each behind its own REST API server."""
    engines, servers, urls = [], [], []
    for i in range(2):
        eng = _engine()
        api = ApiServer(default_config(), engine=eng)
        port = api.start(host="127.0.0.1", port=0)
        engines.append(eng)
        servers.append(api)
        urls.append(f"http://127.0.0.1:{port}")
    return engines, servers, urls


def test_http_client_generates():
    engines, servers, urls = _serve_pair()
    try:
        client = HttpEngineClient(urls[0])
        assert client.healthy()
        msg = Message(id="t1", content="hello transport", user_id="u")
        client.process_fn(None, msg)
        assert msg.response == "hello transport"   # echo engine
        assert msg.metadata["usage"]["completion_tokens"] > 0
    finally:
        for s in servers:
            s.stop()
        for e in engines:
            e.stop()


def test_http_client_reports_dead_engine_unhealthy():
    engines, servers, urls = _serve_pair()
    try:
        client = HttpEngineClient(urls[0])
        assert client.healthy()
        engines[0].stop()      # server still up; engine thread gone
        assert not client.healthy()
    finally:
        for s in servers:
            s.stop()
        for e in engines:
            e.stop()


def test_gateway_routes_with_affinity_and_failover():
    engines, servers, urls = _serve_pair()
    lb = LoadBalancer(LoadBalancerConfig(strategy="round_robin",
                                         health_check_interval=0.0))
    router = EngineRouter(lb)
    try:
        router.register_remote(urls[0], endpoint_id="eng0")
        router.register_remote(urls[1], endpoint_id="eng1")

        # Conversation affinity: every turn of one conversation lands
        # on the same remote endpoint.
        seen = set()
        for i in range(4):
            msg = Message(id=f"a{i}", content=f"turn {i}", user_id="u",
                          conversation_id="conv-x")
            router.process_fn(None, msg)
            assert msg.response == f"turn {i}"
            seen.add(msg.metadata["endpoint_id"])
        assert len(seen) == 1
        sticky = seen.pop()

        # Kill the sticky endpoint's ENGINE (its HTTP server stays up),
        # advance the health machine, and verify traffic fails over.
        victim = 0 if sticky == "eng0" else 1
        engines[victim].stop()
        for _ in range(4):     # degrade → unhealthy takes 3 failures
            lb.check_health_once()
        msg = Message(id="f1", content="after failover", user_id="u",
                      conversation_id="conv-x")
        router.process_fn(None, msg)
        assert msg.response == "after failover"
        assert msg.metadata["endpoint_id"] != sticky
    finally:
        for s in servers:
            s.stop()
        for e in engines:
            e.stop()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_health(url: str, deadline_s: float = 30.0) -> None:
    deadline = time.time() + deadline_s
    last = None
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(f"{url}/health", timeout=2) as r:
                if r.status == 200:
                    data = json.loads(r.read().decode())
                    if data.get("engine") == "running":
                        return
        except OSError as e:
            last = e
        time.sleep(0.1)
    raise TimeoutError(f"{url} never became healthy: {last}")


def test_two_os_process_serve_failover():
    """Two real ``serve`` processes, one gateway router: dispatch over
    HTTP, then SIGKILL one host and fail over through the probe."""
    ports = [_free_port(), _free_port()]
    env = dict(os.environ)
    env["LLMQ_QUEUE_ENABLE_METRICS"] = "false"
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "llmq_tpu", "--backend", "echo",
             "--host", "127.0.0.1", "--port", str(p), "serve"],
            cwd=REPO, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        for p in ports
    ]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    lb = LoadBalancer(LoadBalancerConfig(strategy="round_robin",
                                         health_check_interval=0.0))
    router = EngineRouter(lb)
    try:
        for u in urls:
            _wait_health(u)
        router.register_remote(urls[0], endpoint_id="host0")
        router.register_remote(urls[1], endpoint_id="host1")

        used = set()
        for i in range(6):
            msg = Message(id=f"m{i}", content=f"req {i}", user_id="u",
                          priority=Priority.HIGH)
            router.process_fn(None, msg)
            assert msg.response == f"req {i}"
            used.add(msg.metadata["endpoint_id"])
        assert used == {"host0", "host1"}   # round-robin over both hosts

        procs[0].send_signal(signal.SIGKILL)
        procs[0].wait(timeout=10)
        for _ in range(4):
            lb.check_health_once()
        for i in range(4):
            msg = Message(id=f"k{i}", content=f"post-kill {i}",
                          user_id="u")
            router.process_fn(None, msg)
            assert msg.response == f"post-kill {i}"
            assert msg.metadata["endpoint_id"] == "host1"
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
