"""Remote-engine HTTP transport (BASELINE config #5's dispatch half).

The reference fabricates worker URLs and never dispatches to them
(scheduler.go:299-301; SURVEY §3.5). These tests prove this framework's
transport is real: a gateway LoadBalancer routes drained messages over
HTTP to peer serve processes — with session affinity, EWMA feedback,
and failover through the health state machine when a peer's engine
dies. The last test runs two genuine OS processes (``python -m
llmq_tpu serve``) behind one gateway router and kills one.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from llmq_tpu.api.server import ApiServer
from llmq_tpu.core.config import LoadBalancerConfig, default_config
from llmq_tpu.core.types import Message, Priority
from llmq_tpu.engine import ByteTokenizer, EchoExecutor, InferenceEngine
from llmq_tpu.loadbalancer import (EngineRouter, HttpEngineClient,
                                   LoadBalancer)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _engine() -> InferenceEngine:
    eng = InferenceEngine(EchoExecutor(batch_size=4), ByteTokenizer(),
                          enable_metrics=False)
    eng.start()
    return eng


def _serve_pair():
    """Two in-process engines, each behind its own REST API server."""
    engines, servers, urls = [], [], []
    for i in range(2):
        eng = _engine()
        api = ApiServer(default_config(), engine=eng)
        port = api.start(host="127.0.0.1", port=0)
        engines.append(eng)
        servers.append(api)
        urls.append(f"http://127.0.0.1:{port}")
    return engines, servers, urls


def test_http_client_generates():
    engines, servers, urls = _serve_pair()
    try:
        client = HttpEngineClient(urls[0])
        assert client.healthy()
        msg = Message(id="t1", content="hello transport", user_id="u")
        client.process_fn(None, msg)
        assert msg.response == "hello transport"   # echo engine
        assert msg.metadata["usage"]["completion_tokens"] > 0
    finally:
        for s in servers:
            s.stop()
        for e in engines:
            e.stop()


def test_http_client_reports_dead_engine_unhealthy():
    engines, servers, urls = _serve_pair()
    try:
        client = HttpEngineClient(urls[0])
        assert client.healthy()
        engines[0].stop()      # server still up; engine thread gone
        assert not client.healthy()
    finally:
        for s in servers:
            s.stop()
        for e in engines:
            e.stop()


def test_gateway_routes_with_affinity_and_failover():
    engines, servers, urls = _serve_pair()
    lb = LoadBalancer(LoadBalancerConfig(strategy="round_robin",
                                         health_check_interval=0.0))
    router = EngineRouter(lb)
    try:
        router.register_remote(urls[0], endpoint_id="eng0")
        router.register_remote(urls[1], endpoint_id="eng1")

        # Conversation affinity: every turn of one conversation lands
        # on the same remote endpoint.
        seen = set()
        for i in range(4):
            msg = Message(id=f"a{i}", content=f"turn {i}", user_id="u",
                          conversation_id="conv-x")
            router.process_fn(None, msg)
            assert msg.response == f"turn {i}"
            seen.add(msg.metadata["endpoint_id"])
        assert len(seen) == 1
        sticky = seen.pop()

        # Kill the sticky endpoint's ENGINE (its HTTP server stays up),
        # advance the health machine, and verify traffic fails over.
        victim = 0 if sticky == "eng0" else 1
        engines[victim].stop()
        for _ in range(4):     # degrade → unhealthy takes 3 failures
            lb.check_health_once()
        msg = Message(id="f1", content="after failover", user_id="u",
                      conversation_id="conv-x")
        router.process_fn(None, msg)
        assert msg.response == "after failover"
        assert msg.metadata["endpoint_id"] != sticky
    finally:
        for s in servers:
            s.stop()
        for e in engines:
            e.stop()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_health(url: str, deadline_s: float = 30.0) -> None:
    deadline = time.time() + deadline_s
    last = None
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(f"{url}/health", timeout=2) as r:
                if r.status == 200:
                    data = json.loads(r.read().decode())
                    if data.get("engine") == "running":
                        return
        except OSError as e:
            last = e
        time.sleep(0.1)
    raise TimeoutError(f"{url} never became healthy: {last}")


def test_two_os_process_serve_failover():
    """Two real ``serve`` processes, one gateway router: dispatch over
    HTTP, then SIGKILL one host and fail over through the probe."""
    ports = [_free_port(), _free_port()]
    env = dict(os.environ)
    env["LLMQ_QUEUE_ENABLE_METRICS"] = "false"
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "llmq_tpu", "--backend", "echo",
             "--host", "127.0.0.1", "--port", str(p), "serve"],
            cwd=REPO, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        for p in ports
    ]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    lb = LoadBalancer(LoadBalancerConfig(strategy="round_robin",
                                         health_check_interval=0.0))
    router = EngineRouter(lb)
    try:
        for u in urls:
            _wait_health(u)
        router.register_remote(urls[0], endpoint_id="host0")
        router.register_remote(urls[1], endpoint_id="host1")

        used = set()
        for i in range(6):
            msg = Message(id=f"m{i}", content=f"req {i}", user_id="u",
                          priority=Priority.HIGH)
            router.process_fn(None, msg)
            assert msg.response == f"req {i}"
            used.add(msg.metadata["endpoint_id"])
        assert used == {"host0", "host1"}   # round-robin over both hosts

        procs[0].send_signal(signal.SIGKILL)
        procs[0].wait(timeout=10)
        for _ in range(4):
            lb.check_health_once()
        for i in range(4):
            msg = Message(id=f"k{i}", content=f"post-kill {i}",
                          user_id="u")
            router.process_fn(None, msg)
            assert msg.response == f"post-kill {i}"
            assert msg.metadata["endpoint_id"] == "host1"
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


# -- probe cause classification + circuit breaker (docs/robustness.md) --


def _free_port_url() -> str:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"http://127.0.0.1:{port}"


def test_probe_refused_fast_fails_and_feeds_breaker():
    """Connection-refused (nothing listens there) is the strongest
    down-signal — it must be classified as such and feed the breaker,
    so the data path stops paying connect timeouts between health
    ticks."""
    from llmq_tpu.loadbalancer.circuit_breaker import (BreakerState,
                                                       CircuitBreaker)
    url = _free_port_url()
    br = CircuitBreaker(url, failure_threshold=2, base_backoff=0.05)
    client = HttpEngineClient(url, probe_timeout=0.5, breaker=br)
    assert client.probe() == "refused"
    assert br.consecutive_failures == 1
    assert client.probe() == "refused"
    assert br.state == BreakerState.OPEN   # tripped from probes alone
    assert not client.healthy()


def test_probe_draining_and_stopped_are_not_endpoint_faults():
    """A draining peer and a stopped engine are deliberate states, not
    breaker-worthy faults — and each gets its own verdict."""
    from llmq_tpu.loadbalancer.circuit_breaker import CircuitBreaker
    engines, servers, urls = _serve_pair()
    try:
        br = CircuitBreaker(urls[0], failure_threshold=1)
        client = HttpEngineClient(urls[0], breaker=br)
        assert client.probe() == "ok"
        servers[0].draining = True
        assert client.probe() == "draining"
        assert not client.healthy()
        servers[0].draining = False
        engines[0].stop()
        assert client.probe() == "stopped"
        assert not client.healthy()
        assert br.consecutive_failures == 0   # breaker untouched
    finally:
        for s in servers:
            s.stop()
        for e in engines:
            if e.running:
                e.stop()


def test_expired_deadline_raises_timeout_without_dispatching():
    """An already-expired context must raise TimeoutError BEFORE any
    network I/O: the URL points at a closed port, so an attempted
    dispatch would surface as RuntimeError('unreachable') instead."""
    client = HttpEngineClient(_free_port_url())

    class _Expired:
        def remaining(self):
            return -0.5

    with pytest.raises(TimeoutError):
        client.process_fn(_Expired(), Message(id="dx", content="x",
                                              user_id="u"))


def test_open_breaker_fast_fails_dispatch_without_io():
    from llmq_tpu.loadbalancer.circuit_breaker import (CircuitBreaker,
                                                       CircuitOpenError)
    url = _free_port_url()
    br = CircuitBreaker(url, failure_threshold=1, base_backoff=30.0)
    br.record_failure()                   # OPEN for ~30s
    client = HttpEngineClient(url, timeout=30.0, breaker=br)
    t0 = time.monotonic()
    with pytest.raises(CircuitOpenError):
        client.process_fn(None, Message(id="cb", content="x",
                                        user_id="u"))
    assert time.monotonic() - t0 < 0.5    # no socket was opened


def test_probe_success_resets_consecutive_failures():
    """Sparse refusals (one per replica restart, days apart) must not
    read as consecutive: a clean probe records success."""
    from llmq_tpu.loadbalancer.circuit_breaker import CircuitBreaker
    engines, servers, urls = _serve_pair()
    dead_url = _free_port_url()
    try:
        br = CircuitBreaker(urls[0], failure_threshold=2)
        up = HttpEngineClient(urls[0], breaker=br)
        down = HttpEngineClient(dead_url, probe_timeout=0.5, breaker=br)
        assert down.probe() == "refused"
        assert br.consecutive_failures == 1
        assert up.probe() == "ok"          # healthy gap resets the streak
        assert br.consecutive_failures == 0
        assert down.probe() == "refused"   # 2 sparse refusals: no trip
        assert br.state.value == "closed"
    finally:
        for s in servers:
            s.stop()
        for e in engines:
            e.stop()
