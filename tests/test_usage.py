"""Usage plane (observability/usage.py, docs/observability.md "Usage &
goodput"): per-request device-second attribution, KV page-seconds with
fractional shared-page billing, waste decomposition, tenant bounding,
goodput, the API surface — and the conservation invariant: everything
the device telemetry measured is attributed somewhere (useful + waste +
explicitly-unattributed), within 1 %, on echo and CPU-JAX engines,
including chaos traffic."""

import threading
import time

import pytest

from llmq_tpu.core.types import Message, Priority
from llmq_tpu.engine.engine import GenRequest, InferenceEngine
from llmq_tpu.engine.executor import EchoExecutor
from llmq_tpu.engine.tokenizer import ByteTokenizer
from llmq_tpu.observability.recorder import get_recorder
from llmq_tpu.observability.usage import (PageUsageTracker, RequestUsage,
                                          UsageLedger, get_usage_ledger,
                                          reset_usage, sanitize_tenant)


@pytest.fixture(autouse=True)
def _clean_ledger():
    reset_usage()
    led = get_usage_ledger()
    led.reconfigure(enabled=True, max_tenants=64)
    yield
    reset_usage()


def make_echo_engine(name="usage-echo", slots=4, chunk=4, **kw):
    tok = ByteTokenizer()
    ex = EchoExecutor(batch_size=slots, page_size=8, num_pages=256,
                      max_pages_per_seq=16, eos_id=tok.eos_id,
                      chunk_size=chunk, mixed_prefill_slices=2,
                      mixed_slice_tokens=8)
    return InferenceEngine(ex, tok, name=name, enable_metrics=False,
                           max_decode_steps=64, **kw)


def _conservation(engines, led):
    """Measured device time vs ledger attribution, both in seconds."""
    measured = sum(e._telemetry._device.total_ms for e in engines) / 1e3
    accounted = (led.attributed_device_s + led.unattributed_device_s)
    return measured, accounted


# -- page-seconds tracker (satellite: shared-page attribution) -----------------


class TestPageUsageTracker:
    def test_exclusive_pages_accumulate(self):
        tr = PageUsageTracker()
        tr.update("a", 4)
        time.sleep(0.05)
        got = tr.close("a")
        assert got == pytest.approx(4 * 0.05, rel=0.5)

    def test_shared_pages_split_fractionally_never_double_counted(self):
        tr = PageUsageTracker()
        # Two sharers of pages {10, 11} plus one exclusive page each.
        tr.update("a", 1, shared=(10, 11))
        tr.update("b", 1, shared=(10, 11))
        time.sleep(0.08)
        a = tr.close("a")
        b = tr.close("b")
        # Each holder: 1 exclusive + 2 shared/2 = 2 page-rates.
        assert a == pytest.approx(b, rel=0.3)
        # Physical pages alive: 2 exclusive + 2 shared = 4 page-rates
        # total — the shared pages are charged ONCE across sharers.
        assert a + b == pytest.approx(4 * 0.08, rel=0.5)

    def test_resplit_when_a_sharer_completes(self):
        tr = PageUsageTracker()
        tr.update("a", 0, shared=(7,))
        tr.update("b", 0, shared=(7,))
        time.sleep(0.06)
        first = tr.close("a")              # a paid 1/2 of page 7 so far
        time.sleep(0.06)
        second = tr.close("b")             # b: 1/2 then the whole page
        assert first == pytest.approx(0.03, rel=0.6)
        assert second == pytest.approx(0.03 + 0.06, rel=0.6)

    def test_close_unknown_key_is_zero(self):
        assert PageUsageTracker().close("nope") == 0.0

    def test_update_is_idempotent_for_membership(self):
        tr = PageUsageTracker()
        tr.update("a", 2, shared=(5,))
        tr.update("a", 2, shared=(5,))     # same holding, re-announced
        time.sleep(0.03)
        got = tr.close("a")
        assert got == pytest.approx(3 * 0.03, rel=0.6)
        assert tr.holders() == 0


# -- ledger unit behavior ------------------------------------------------------


class TestLedger:
    def test_finalize_ok_keeps_device_time_useful(self):
        led = UsageLedger()
        ru = RequestUsage()
        ru.device_s = 2.0
        out = led.finalize("r1", ru, tenant="t1", priority="normal",
                           engine="e0", tokens=10, ok=True)
        assert out["device_seconds"] == 2.0
        assert out["waste_seconds"] == 0.0
        snap = led.snapshot()
        assert snap["tenants"]["t1"]["device_seconds"] == 2.0
        assert snap["totals"]["waste_device_seconds"] == 0

    def test_finalize_failure_reclassifies_all_as_waste(self):
        led = UsageLedger()
        ru = RequestUsage()
        ru.device_s = 1.5
        ru.waste_s = 0.5
        out = led.finalize("r1", ru, tenant="t1", priority="normal",
                           engine="e0", ok=False, waste_reason="crash")
        assert out["device_seconds"] == 0.0
        assert out["waste_seconds"] == 2.0
        assert out["waste_reason"] == "crash"
        assert led.snapshot()["waste_by_reason"]["crash"] == 2.0

    def test_note_retry_reclassifies_before_flush(self):
        led = UsageLedger()
        ru = RequestUsage()
        ru.device_s = 1.0
        led.finalize("r1", ru, tenant="t", priority="low", engine="e",
                     ok=False)
        led.note_retry("r1")
        wb = led.snapshot()["waste_by_reason"]
        assert wb.get("retry") == 1.0
        assert wb.get("error", 0.0) == 0.0

    def test_note_failover_parks_cause_when_announced_first(self):
        led = UsageLedger()
        led.note_failover("r1")            # router beats the engine
        ru = RequestUsage()
        ru.device_s = 0.7
        out = led.finalize("r1", ru, tenant="t", priority="high",
                           engine="e", ok=False)
        assert out["waste_reason"] == "failover"
        assert led.snapshot()["waste_by_reason"]["failover"] == \
            pytest.approx(0.7)

    def test_specific_reasons_are_not_rewritable(self):
        led = UsageLedger()
        ru = RequestUsage()
        ru.device_s = 1.0
        led.finalize("r1", ru, tenant="t", priority="low", engine="e",
                     ok=False, waste_reason="crash")
        led.note_retry("r1")
        assert led.snapshot()["waste_by_reason"] == {"crash": 1.0}

    def test_tenant_label_bounds_and_id_spray_collapse(self):
        led = UsageLedger(max_tenants=3)
        assert led.tenant_label("alpha") == "alpha"
        assert led.tenant_label("beta") == "beta"
        assert led.tenant_label("gamma") == "gamma"
        assert led.tenant_label("delta") == "other"     # over the bound
        assert led.tenant_label("alpha") == "alpha"     # registered stays
        # id-shaped tenants never become labels, even under the bound.
        led2 = UsageLedger(max_tenants=100)
        assert led2.tenant_label(
            "8c94e42e-6f3f-4a73-a18f-000000000001") == "other"
        assert led2.tenant_label("1234567890") == "other"

    def test_sanitize_tenant(self):
        assert sanitize_tenant("") == "default"
        assert sanitize_tenant(None) == "default"
        assert sanitize_tenant("  team-a  ") == "team-a"
        assert len(sanitize_tenant("x" * 500)) == 64

    def test_conversation_rollup_is_lru_bounded(self):
        led = UsageLedger(max_conversations=2)
        for i in range(4):
            ru = RequestUsage()
            ru.device_s = 0.1
            led.finalize(f"r{i}", ru, tenant="t", priority="low",
                         engine="e", conversation=f"c{i}", ok=True)
        convs = led.snapshot()["conversations"]
        assert set(convs) == {"c2", "c3"}

    def test_disabled_ledger_records_nothing_via_notes(self):
        led = UsageLedger(enabled=False)
        led.note_retry("r1")
        led.note_failover("r2")
        led.pin_kv("c", 5, "t")
        led.unpin_kv("c")
        assert led.snapshot()["waste_by_reason"] == {}
        assert led.pinned_kv_page_s == 0.0


# -- engine attribution: conservation invariant --------------------------------


class TestEchoConservation:
    def test_attribution_conserves_measured_device_time(self):
        led = get_usage_ledger()
        eng = make_echo_engine("usage-c1")
        hs = [eng.submit(GenRequest(
                  id=f"c{i}", prompt=f"conservation prompt {i} " * (i + 1),
                  priority=Priority.NORMAL, max_new_tokens=16,
                  tenant_id=f"tenant-{i % 3}"))
              for i in range(12)]
        eng.run_until_idle()
        assert all(h.result.finish_reason in ("eos", "length")
                   for h in hs)
        measured, accounted = _conservation([eng], led)
        assert measured > 0
        assert accounted == pytest.approx(measured, rel=0.01)
        # Finalized records sum to the attributed part.
        snap = led.snapshot()
        t = snap["totals"]
        finalized = (t["useful_device_seconds"]
                     + t["waste_device_seconds"])
        assert finalized == pytest.approx(led.attributed_device_s,
                                          rel=0.01)
        assert snap["tenants"].keys() == {
            "tenant-0", "tenant-1", "tenant-2"}

    def test_conservation_with_chaos_crash_and_cancel(self):
        """Chaos-shaped traffic: a mid-flight engine crash recovery and
        client cancellations — the wasted device time lands in
        usage_waste_seconds (crash / cancelled), not silently dropped,
        and the invariant still holds."""
        led = get_usage_ledger()
        eng = make_echo_engine("usage-c2")
        hs = [eng.submit(GenRequest(
                  id=f"x{i}", prompt="chaos conservation " * 4,
                  priority=Priority.NORMAL, max_new_tokens=32))
              for i in range(6)]
        for _ in range(8):                 # partial progress
            eng.step()
        hs[0].cancel()                     # client gave up
        eng.step()
        eng.step()
        # Crash recovery: every in-flight handle fails over with its
        # accumulated device time classified as crash waste.
        out = eng.recover_after_crash()
        assert out["recovered"] > 0
        measured, accounted = _conservation([eng], led)
        assert measured > 0
        assert accounted == pytest.approx(measured, rel=0.01)
        wb = led.snapshot()["waste_by_reason"]
        assert wb.get("crash", 0.0) > 0.0
        assert sum(wb.values()) > 0.0

    def test_retry_waste_reaches_the_metric_counter(self):
        """The worker's retry decision relabels the failed attempt's
        waste; after a flush the prometheus counter carries it."""
        led = get_usage_ledger()
        eng = make_echo_engine("usage-c3")
        h = eng.submit(GenRequest(id="retry-1",
                                  prompt="will be cancelled " * 8,
                                  max_new_tokens=48))
        for _ in range(6):
            eng.step()
        h.cancel()                         # worker-timeout path shape
        eng.run_until_idle()
        led.note_retry("retry-1")          # worker schedules the retry
        assert led.snapshot()["waste_by_reason"].get("retry", 0) > 0
        from llmq_tpu.metrics.registry import REGISTRY
        before = REGISTRY.get_sample_value(
            "llm_queue_usage_waste_seconds_total",
            {"reason": "retry"}) or 0.0
        led.metrics_enabled = True
        led.flush()
        after = REGISTRY.get_sample_value(
            "llm_queue_usage_waste_seconds_total", {"reason": "retry"})
        assert after is not None and after > before

    def test_preempt_shed_waste_attributed(self):
        """A low-tier sequence is slot-preempted by a realtime arrival,
        then loses its parked pages to pool pressure ("shed"); its
        rebuild re-prefill — run through mixed iterations while the
        other rows decode — is billed as shed waste, while the request
        still completes and keeps its useful time."""
        from llmq_tpu.core.config import MixedBatchConfig
        led = get_usage_ledger()
        tok = ByteTokenizer()
        ex = EchoExecutor(batch_size=2, page_size=8, num_pages=14,
                          max_pages_per_seq=16, eos_id=tok.eos_id,
                          chunk_size=4, mixed_prefill_slices=2,
                          mixed_slice_tokens=8)
        eng = InferenceEngine(
            ex, tok, name="usage-shed", enable_metrics=False,
            max_decode_steps=64,
            mixed_batch=MixedBatchConfig(enabled=True,
                                         prefill_token_budget=16,
                                         max_slices=2))
        x = eng.submit(GenRequest(id="x", prompt="x" * 32,
                                  priority=Priority.NORMAL,
                                  max_new_tokens=32))
        low = eng.submit(GenRequest(id="low", prompt="y" * 16,
                                    priority=Priority.LOW,
                                    max_new_tokens=16))
        for _ in range(4):
            eng.step()
        rt = eng.submit(GenRequest(id="rt", prompt="z" * 16,
                                   priority=Priority.REALTIME,
                                   max_new_tokens=16))
        eng.run_until_idle()
        for h in (x, low, rt):
            assert h.result.finish_reason in ("eos", "length")
        measured, accounted = _conservation([eng], led)
        assert accounted == pytest.approx(measured, rel=0.01)
        wb = led.snapshot()["waste_by_reason"]
        assert (wb.get("preempt", 0.0) + wb.get("shed", 0.0)) > 0.0
        # The shed request still delivered output: its useful time
        # survives next to its waste.
        rec = led.get("low")
        assert rec is not None and rec["device_seconds"] > 0
        assert rec["waste_seconds"] > 0


class TestJaxConservation:
    def test_attribution_conserves_on_cpu_jax_engine(self):
        """The invariant on the real executor: measured step_device_ms
        vs attributed+unattributed, within 1 %, chaos included (a
        cancellation mid-decode)."""
        import jax

        from llmq_tpu.engine.executor import JaxExecutor
        from llmq_tpu.models.llama import get_config, init_params
        led = get_usage_ledger()
        cfg = get_config("llama3-tiny", max_seq_len=256, vocab_size=512)
        params = init_params(jax.random.PRNGKey(0), cfg)
        tok = ByteTokenizer()
        ex = JaxExecutor(cfg, params, batch_size=3, page_size=8,
                         num_pages=96, prefill_buckets=[16, 64],
                         eos_id=tok.eos_id, chunk_size=4)
        eng = InferenceEngine(ex, tok, name="usage-jax",
                              enable_metrics=False, max_decode_steps=12)
        hs = [eng.submit(GenRequest(
                  id=f"j{i}", prompt=f"jax conservation {i}",
                  priority=Priority.NORMAL, max_new_tokens=10,
                  tenant_id="jax-tenant"))
              for i in range(4)]
        for _ in range(3):
            eng.step()
        hs[0].cancel()                     # chaos: client went away
        eng.run_until_idle()
        assert all(h.done for h in hs)
        measured, accounted = _conservation([eng], led)
        assert measured > 0
        assert accounted == pytest.approx(measured, rel=0.01)
        snap = led.snapshot()
        assert snap["tenants"]["jax-tenant"]["requests"] == 4


class TestKvPageSeconds:
    def test_kv_page_seconds_scale_with_holding_time(self):
        led = get_usage_ledger()
        eng = make_echo_engine("usage-kv", chunk=1)
        h = eng.submit(GenRequest(id="kv1", prompt="hold pages " * 6,
                                  max_new_tokens=8))
        # Drip-feed steps so the pages are held across real wall time.
        for _ in range(40):
            eng.step()
            if h.done:
                break
            time.sleep(0.002)
        eng.run_until_idle()
        rec = led.get("kv1")
        assert rec is not None
        assert rec["kv_page_seconds"] > 0

    def test_pinned_conversation_kv_billed_to_tenant(self):
        led = get_usage_ledger()
        eng = make_echo_engine("usage-pin")
        h = eng.submit(GenRequest(id="p1", prompt="turn one " * 4,
                                  conversation_id="conv-pin",
                                  max_new_tokens=6,
                                  tenant_id="pinned-tenant"))
        eng.run_until_idle()
        assert h.result.finish_reason in ("eos", "length")
        time.sleep(0.05)                   # pinned residency window
        eng.drop_conversation("conv-pin")  # TTL/eviction shape
        assert led.pinned_kv_page_s > 0
        snap = led.snapshot()
        assert snap["tenants"]["pinned-tenant"]["kv_page_seconds"] > 0


# -- goodput -------------------------------------------------------------------


class TestGoodput:
    def test_goodput_joins_slo_verdict_with_device_time(self):
        led = get_usage_ledger()
        rec = get_recorder()
        rec.clear()
        eng = make_echo_engine("usage-gp")
        hs = [eng.submit(GenRequest(
                  id=f"g{i}", prompt="goodput join " * 3,
                  priority=Priority.NORMAL, max_new_tokens=8))
              for i in range(5)]
        eng.run_until_idle()
        assert all(h.done for h in hs)
        rec.flush_metrics()                # drives the join
        gp = led.goodput()
        assert gp["requests"] == 5
        assert gp["slo_met_requests"] == 5
        assert gp["tokens_slo_met"] > 0
        assert gp["tokens_per_device_second"] > 0

    def test_failed_requests_drag_goodput_down(self):
        led = get_usage_ledger()
        rec = get_recorder()
        rec.clear()
        eng = make_echo_engine("usage-gp2")
        h = eng.submit(GenRequest(id="gbad", prompt="doomed " * 6,
                                  max_new_tokens=32))
        for _ in range(6):
            eng.step()
        h.cancel()
        eng.run_until_idle()
        rec.flush_metrics()
        gp = led.goodput()
        assert gp["requests"] == 1
        assert gp["slo_met_requests"] == 0
        assert gp["device_seconds"] > 0          # waste in denominator
        assert gp["tokens_per_device_second"] == 0.0


# -- surfaces: handle / worker metadata / trace / API --------------------------


class TestSurfaces:
    def test_finished_handle_carries_usage(self):
        eng = make_echo_engine("usage-s1")
        h = eng.submit(GenRequest(id="s1", prompt="surface " * 3,
                                  max_new_tokens=6, tenant_id="acme"))
        eng.run_until_idle()
        assert h.usage is not None
        assert h.usage["tenant"] == "acme"
        assert h.usage["device_seconds"] > 0

    def test_process_fn_merges_usage_into_message_metadata(self):
        eng = make_echo_engine("usage-s2")
        eng.start()
        try:
            msg = Message(id="s2", content="worker seam " * 3,
                          tenant_id="acme")
            msg.metadata["max_new_tokens"] = 6
            eng.process_fn(None, msg)
        finally:
            eng.stop()
        u = msg.metadata["usage"]
        assert u["completion_tokens"] > 0          # pre-existing keys
        assert u["device_seconds"] > 0             # attribution keys
        assert u["tenant"] == "acme"

    def test_trace_summary_shows_cost_next_to_latency(self):
        rec = get_recorder()
        rec.clear()
        eng = make_echo_engine("usage-s3")
        h = eng.submit(GenRequest(id="s3-trace", prompt="cost " * 4,
                                  max_new_tokens=6))
        eng.run_until_idle()
        assert h.done
        tl = rec.get("s3-trace")
        assert tl is not None
        summ = tl.summary()
        assert summ["tokens"]["completion"] > 0
        assert summ["usage"]["device_seconds"] > 0
        full = tl.to_dict()
        assert full["usage"]["device_seconds"] > 0
        assert full["tokens"]["prompt"] > 0

    def test_usage_api_route_and_tenant_header(self):
        from llmq_tpu.api.server import ApiServer
        from llmq_tpu.core.config import default_config
        eng = make_echo_engine("usage-s4")
        eng.start()
        api = ApiServer(default_config(), engine=eng)
        try:
            import json
            status, payload, _ = api.dispatch(
                "POST", "/api/v1/messages",
                json.dumps({"id": "s4-hdr", "content": "via header",
                            "stream": True,
                            "max_new_tokens": 4}).encode(),
                headers={"X-Tenant-Id": "header-tenant"})
            assert status == 200
            events = list(payload)         # drain the SSE stream
            done = [e for e in events if e.startswith("event: done")]
            assert done, events
            body = json.loads(done[0].split("data: ", 1)[1])
            assert body["usage"]["tenant"] == "header-tenant"
            assert body["usage"]["device_seconds"] >= 0
            status, snap, _ = api.dispatch("GET", "/api/v1/usage", b"")
            assert status == 200
            assert "header-tenant" in snap["tenants"]
            assert "goodput" in snap
            status, one, _ = api.dispatch(
                "GET", "/api/v1/usage?tenant=header-tenant", b"")
            assert status == 200
            assert one["usage"]["requests"] >= 1
        finally:
            eng.stop()

    def test_engine_stats_route_carries_usage(self):
        from llmq_tpu.api.server import ApiServer
        from llmq_tpu.core.config import default_config
        eng = make_echo_engine("usage-s5")
        hs = [eng.submit(GenRequest(id=f"s5-{i}", prompt="stats",
                                    max_new_tokens=4))
              for i in range(2)]
        eng.run_until_idle()
        assert all(h.done for h in hs)
        api = ApiServer(default_config(), engine=eng)
        status, payload, _ = api.dispatch("GET", "/api/v1/engine/stats",
                                          b"")
        assert status == 200
        assert payload["usage"]["totals"]["requests"] >= 2

    def test_cluster_overview_aggregates_usage(self):
        from llmq_tpu.cluster.router import ClusterRouter
        from llmq_tpu.core.config import ClusterConfig
        from llmq_tpu.loadbalancer.load_balancer import LoadBalancer
        eng = make_echo_engine("usage-s6")
        hs = [eng.submit(GenRequest(id=f"s6-{i}", prompt="overview",
                                    max_new_tokens=4))
              for i in range(3)]
        eng.run_until_idle()
        assert all(h.done for h in hs)
        router = ClusterRouter(LoadBalancer(), config=ClusterConfig(),
                               enable_metrics=False)
        router.register_engine(eng)
        out = router.overview()
        agg = out["aggregate"]["usage"]
        assert agg["reporting"] == 1
        assert agg["device_seconds"] > 0
        assert out["replicas"][0]["usage"]["totals"]["requests"] >= 3


# -- hard off-switch -----------------------------------------------------------


class TestOffSwitch:
    def test_disabled_plane_records_nothing(self):
        led = get_usage_ledger()
        led.reconfigure(enabled=False)
        eng = make_echo_engine("usage-off")
        h = eng.submit(GenRequest(id="off1", prompt="dark " * 3,
                                  max_new_tokens=6))
        eng.run_until_idle()
        assert h.result.finish_reason in ("eos", "length")
        assert h.usage is None
        assert led.total_device_s == 0.0
        assert led.requests_finalized == 0
        assert led.tracker.holders() == 0

    def test_usage_route_503_when_disabled(self):
        from llmq_tpu.api.server import ApiServer
        from llmq_tpu.core.config import default_config
        get_usage_ledger().reconfigure(enabled=False)
        api = ApiServer(default_config())
        status, payload, _ = api.dispatch("GET", "/api/v1/usage", b"")
        assert status == 503

    def test_config_wiring(self):
        from llmq_tpu.core.config import default_config
        from llmq_tpu.observability.recorder import configure
        cfg = default_config()
        cfg.observability.usage.enabled = False
        cfg.observability.usage.max_tenants = 7
        configure(cfg.observability)
        led = get_usage_ledger()
        assert led.enabled is False
        assert led.max_tenants == 7
        cfg.observability.usage.enabled = True
        configure(cfg.observability)
        assert led.enabled is True


# -- overhead guard (the plane must stay off the step hot path) ----------------


class TestOverheadGuard:
    def test_charge_step_under_3pct_of_echo_request(self):
        """Mirrors the PR-3/PR-6 guards: measure one echo request
        end-to-end, then the per-chunk cost of the usage charge path
        (_charge_step with a realistic part list), and require
        chunks-per-request x per-call < 3 % of the request."""
        eng = make_echo_engine("usage-oh", chunk=1)
        n, max_new = 24, 16
        t0 = time.perf_counter()
        hs = [eng.submit(GenRequest(id=f"oh{i}", prompt="overhead " * 2,
                                    max_new_tokens=max_new))
              for i in range(n)]
        eng.run_until_idle()
        assert all(h.done for h in hs)
        per_request = (time.perf_counter() - t0) / n
        calls_per_request = (
            eng.get_stats()["device"]["steps"]["count"] / n)

        probe = make_echo_engine("usage-oh-probe")
        seqs = []
        for i in range(4):
            h = probe.submit(GenRequest(id=f"p{i}", prompt="x",
                                        max_new_tokens=4))
            seqs.append(h)
        with probe._mu:
            rows = list(probe._inbox)
        parts = [(s, 4, False) for s in rows]
        per_call = float("inf")
        for _ in range(5):
            m = 2000
            t0 = time.perf_counter()
            for _ in range(m):
                probe._charge_step(1e-4, parts)
            per_call = min(per_call,
                           (time.perf_counter() - t0) / m)
        cost = calls_per_request * per_call
        assert cost < 0.03 * per_request, (
            f"usage charging {cost * 1e6:.1f}us/request "
            f"({calls_per_request:.1f} chunks x {per_call * 1e6:.1f}us)"
            f" vs request {per_request * 1e6:.1f}us")


# -- tracker concurrency -------------------------------------------------------


class TestTrackerConcurrency:
    def test_concurrent_updates_and_closes_stay_consistent(self):
        tr = PageUsageTracker()
        stop = threading.Event()
        errs = []

        def churn(key):
            try:
                i = 0
                while not stop.is_set():
                    tr.update(key, i % 3, shared=(1, 2))
                    i += 1
                tr.close(key)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=churn, args=(f"k{i}",))
              for i in range(4)]
        for t in ts:
            t.start()
        time.sleep(0.15)
        stop.set()
        for t in ts:
            t.join(timeout=5)
        assert not errs
        assert tr.holders() == 0
