"""Queue durability (write-ahead log) — capability the reference lacks:
its queues are memory-only and every pending message dies on restart
(SURVEY §5; its README claims Redis queueing it never implements)."""

import json
import threading

import pytest

from llmq_tpu.core.config import default_config
from llmq_tpu.core.types import Message, Priority
from llmq_tpu.queueing.queue_manager import QueueManager
from llmq_tpu.queueing.wal import QueueWAL


def mk(mid, prio=Priority.NORMAL, content="x"):
    return Message(id=mid, content=content, user_id="u", priority=prio)


class TestQueueWAL:
    def test_pending_survive_restart_in_order(self, tmp_path):
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)
        for i, p in enumerate([Priority.LOW, Priority.REALTIME,
                               Priority.NORMAL, Priority.REALTIME]):
            qm.push_message(mk(f"m{i}", p))
        qm.stop()

        qm2 = QueueManager("m", enable_metrics=False, wal_path=wal)
        assert qm2.total_pending() == 4
        # Priority + FIFO order preserved across restart.
        drained = qm2.drain_in_priority_order(10)
        assert [m.id for m in drained] == ["m1", "m3", "m2", "m0"]
        qm2.stop()

    def test_completed_not_restored_inflight_redelivered(self, tmp_path):
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)
        for i in range(4):
            qm.push_message(mk(f"m{i}"))
        a = qm.pop_message("normal")
        qm.pop_message("normal")           # "b": popped, never completed
        qm.complete_message(a, 0.1)        # finished → gone
        # b popped but never completed → crash → must redeliver
        qm.stop()

        qm2 = QueueManager("m", enable_metrics=False, wal_path=wal)
        restored = {m.id for m in qm2.drain_in_priority_order(10)}
        assert a.id not in restored
        assert restored == {"m1", "m2", "m3"}
        qm2.stop()

    def test_requeue_and_remove_ops(self, tmp_path):
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)
        qm.push_message(mk("a"))
        qm.push_message(mk("b"))
        m = qm.pop_message("normal")
        qm.requeue_message(m)              # back to pending
        qm.remove_message("b")             # admin-removed → gone
        qm.stop()

        qm2 = QueueManager("m", enable_metrics=False, wal_path=wal)
        restored = [m.id for m in qm2.drain_in_priority_order(10)]
        assert restored == ["a"]
        qm2.stop()

    def test_corrupt_trailing_line_skipped(self, tmp_path):
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)
        qm.push_message(mk("good"))
        qm.stop()
        with open(wal, "a") as f:
            f.write('{"op": "push", "q": "normal", "id": "torn", "ms')
        qm2 = QueueManager("m", enable_metrics=False, wal_path=wal)
        assert [m.id for m in qm2.drain_in_priority_order(10)] == ["good"]
        qm2.stop()

    def test_restart_compacts_journal(self, tmp_path):
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)
        for i in range(50):
            qm.push_message(mk(f"m{i}"))
        for m in qm.drain_in_priority_order(49):
            qm.complete_message(m, 0.0)
        qm.stop()
        lines_before = sum(1 for _ in open(wal))
        assert lines_before >= 148          # 50 push + 49 pop + 49 done
        qm2 = QueueManager("m", enable_metrics=False, wal_path=wal)
        lines_after = sum(1 for _ in open(wal))
        assert lines_after == 1             # only the live message
        rec = json.loads(open(wal).readline())
        assert rec["op"] == "push" and rec["id"] == "m49"
        qm2.stop()

    def test_message_fields_roundtrip(self, tmp_path):
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)
        msg = mk("rich", Priority.HIGH, content="hello wörld")
        msg.conversation_id = "c9"
        msg.metadata["k"] = "v"
        qm.push_message(msg)
        qm.stop()
        qm2 = QueueManager("m", enable_metrics=False, wal_path=wal)
        got = qm2.pop_message("high")
        assert got.content == "hello wörld"
        assert got.conversation_id == "c9"
        assert got.metadata["k"] == "v"
        qm2.stop()

    def test_concurrent_appends_safe(self, tmp_path):
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)

        def push_many(base):
            for i in range(50):
                qm.push_message(mk(f"{base}-{i}"))

        ts = [threading.Thread(target=push_many, args=(b,))
              for b in ("a", "b", "c")]
        [t.start() for t in ts]
        [t.join() for t in ts]
        qm.stop()
        qm2 = QueueManager("m", enable_metrics=False, wal_path=wal)
        assert qm2.total_pending() == 150
        qm2.stop()

    def test_factory_wal_dir_wiring(self, tmp_path):
        from llmq_tpu.queueing.factory import QueueFactory, QueueType
        cfg = default_config()
        cfg.queue.enable_metrics = False
        cfg.queue.wal_dir = str(tmp_path)
        fac = QueueFactory(cfg)
        man = fac.create_queue_manager("std", QueueType.STANDARD)
        man.push_message(mk("f1"))
        fac.stop_all()
        assert (tmp_path / "std.wal").exists()
        fac2 = QueueFactory(cfg)
        man2 = fac2.create_queue_manager("std", QueueType.STANDARD)
        assert man2.total_pending() == 1
        fac2.stop_all()

    def test_monitor_compacts_running_journal(self, tmp_path):
        """Long-running process: the monitor tick rewrites the journal
        once dead records dominate (finding: compaction was restart-only
        → unbounded growth)."""
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)
        qm.qconfig.stale_message_age = 0          # isolate compaction
        for i in range(400):
            qm.push_message(mk(f"m{i}"))
        for m in qm.drain_in_priority_order(399):
            qm.complete_message(m, 0.0)
        assert sum(1 for _ in open(wal)) >= 1100
        qm.run_monitor_once()
        assert sum(1 for _ in open(wal)) == 1      # only m399 lives
        qm.stop()

    def test_stale_expiry_not_resurrected(self, tmp_path, fake_clock):
        """Expired-stale messages must not come back on restart."""
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal,
                          clock=fake_clock)
        qm.qconfig.stale_message_age = 10.0
        qm.push_message(mk("old"))
        fake_clock.advance(100.0)
        qm.push_message(mk("fresh"))
        qm.run_monitor_once()                     # expires "old"
        qm.stop()
        qm2 = QueueManager("m", enable_metrics=False, wal_path=wal,
                           clock=fake_clock)
        assert [m.id for m in qm2.drain_in_priority_order(10)] == ["fresh"]
        qm2.stop()

    def test_restore_overflow_drops_not_crashes(self, tmp_path):
        """More live WAL records than queue capacity must not prevent
        startup — overflow drops loudly, service comes up."""
        cfg = default_config()
        cfg.queue.max_queue_size = 5
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", config=cfg, enable_metrics=False,
                          wal_path=wal)
        for i in range(5):
            qm.push_message(mk(f"m{i}"))
        # Two popped-but-unfinished on top of a full queue → 7 live.
        qm.pop_message("normal")
        qm.pop_message("normal")
        qm.push_message(mk("m5"))
        qm.push_message(mk("m6"))
        qm.stop()
        qm2 = QueueManager("m", config=cfg, enable_metrics=False,
                           wal_path=wal)
        assert qm2.total_pending() == 5            # capacity, no crash
        qm2.stop()

    def test_compaction_concurrent_push_not_erased(self, tmp_path):
        """ADVICE r2 (medium): a message journaled while the monitor is
        compacting must survive the rewrite. Deterministic version:
        stall the live-set snapshot mid-compaction and prove a
        concurrent push blocks until the snapshot finishes (after which
        it is either buffered-and-replayed into the new journal or
        lands after the swap), instead of racing it."""
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)
        qm.qconfig.stale_message_age = 0
        for i in range(600):
            qm.push_message(mk(f"m{i}"))
        for m in qm.drain_in_priority_order(600):
            qm.complete_message(m, 0.0)

        in_snapshot = threading.Event()
        release = threading.Event()
        orig_snapshot = qm.queue.snapshot

        def stalling_snapshot(qname):
            in_snapshot.set()
            release.wait(5.0)
            return orig_snapshot(qname)

        qm.queue.snapshot = stalling_snapshot
        compact = threading.Thread(target=qm.run_monitor_once)
        compact.start()
        assert in_snapshot.wait(5.0)
        pushed = threading.Event()
        pusher = threading.Thread(
            target=lambda: (qm.push_message(mk("late")), pushed.set()))
        pusher.start()
        # The push must be blocked by the compaction lock...
        assert not pushed.wait(0.3)
        release.set()
        compact.join(5.0)
        pusher.join(5.0)
        assert pushed.is_set()
        qm.stop()
        # ...and after a crash+replay the late push is still live.
        restored = QueueWAL.replay(wal)
        assert "late" in [m.id for _, m in restored]

    def test_wedged_push_race_stress(self, tmp_path):
        """Belt-and-braces stress: concurrent pushers + completers +
        monitor compactions; every message not completed must replay."""
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)
        qm.qconfig.stale_message_age = 0
        done = threading.Event()
        completed = []

        def pusher(tag):
            for i in range(300):
                qm.push_message(mk(f"{tag}-{i}"))

        def completer():
            while not done.is_set():
                for m in qm.drain_in_priority_order(16):
                    qm.complete_message(m, 0.0)
                    completed.append(m.id)

        def compactor():
            while not done.is_set():
                qm.run_monitor_once()

        threads = [threading.Thread(target=pusher, args=(t,))
                   for t in ("a", "b")]
        threads += [threading.Thread(target=completer),
                    threading.Thread(target=compactor)]
        for t in threads:
            t.start()
        for t in threads[:2]:
            t.join(30.0)
        done.set()
        for t in threads[2:]:
            t.join(10.0)
        # Drain the rest so "live" is well-defined, then check the WAL
        # replays exactly the still-live set.
        leftover = {m.id for m in qm.drain_in_priority_order(10_000)}
        qm.stop()
        restored = {m.id for _, m in QueueWAL.replay(wal)}
        # Every leftover (never completed) message must be in the WAL.
        assert leftover <= restored
        # Nothing completed may resurrect as pending... popped-but-live
        # redelivery is allowed, completed is not.
        assert not (restored & set(completed) - leftover)

    def test_compaction_aborts_cleanly_on_snapshot_failure(self, tmp_path):
        """A snapshot/serialization failure mid-compaction must abort
        (tmp removed, buffer dropped) — not wedge compaction open or
        leak appends into a dead buffer forever."""
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)
        qm.qconfig.stale_message_age = 0
        for i in range(600):
            qm.push_message(mk(f"m{i}"))
        for m in qm.drain_in_priority_order(600):
            qm.complete_message(m, 0.0)

        def boom(qname):
            raise RuntimeError("snapshot failed")

        orig = qm.queue.snapshot
        qm.queue.snapshot = boom
        with pytest.raises(RuntimeError):
            qm.run_monitor_once()
        # Compaction must be re-attemptable and the buffer closed.
        assert qm._wal._compact_buf is None
        assert not (tmp_path / "q.wal.tmp").exists()
        qm.queue.snapshot = orig
        qm.run_monitor_once()                  # now compacts fine
        assert sum(1 for _ in open(wal)) == 0  # nothing live
        qm.stop()

    def test_rewrite_refuses_during_inflight_compaction(self, tmp_path):
        wal = str(tmp_path / "q.wal")
        w = QueueWAL(wal)
        assert w.begin_compact()
        with pytest.raises(RuntimeError):
            w.rewrite([])
        w.finish_compact(0, commit=False)
        w.rewrite([])                          # fine after abort
        w.close()


class TestCompactionCrashWindow:
    """Satellite: the rename-based compaction swap must be durable —
    a crash at ANY point mid-compaction (before the swap, with a
    truncated tmp file, or right after the swap) must leave a journal
    whose replay reconstructs the live set."""

    def _seed(self, path: str):
        """10 pushes, 4 completed → live set of 6."""
        wal = QueueWAL(path, fsync_every=1)
        msgs = [mk(f"c{i}") for i in range(10)]
        for m in msgs:
            wal.append("push", "normal", m.id, m)
        for m in msgs[:4]:
            wal.append("complete", "normal", m.id)
        live = [("normal", m) for m in msgs[4:]]
        expected = {m.id for m in msgs[4:]}
        return wal, live, expected

    def test_crash_before_swap_with_truncated_tmp(self, tmp_path):
        path = str(tmp_path / "q.wal")
        wal, live, expected = self._seed(path)
        assert wal.begin_compact()
        wal.write_compact_tmp(live)
        # CRASH before finish_compact: the tmp file exists and is even
        # torn mid-record (the torn-write case).
        tmp = path + ".tmp"
        wal._compact_tmp.flush()
        import os
        size = os.path.getsize(tmp)
        with open(tmp, "r+b") as f:
            f.truncate(max(1, size // 2))
        # A fresh process replays the ORIGINAL journal — complete
        # history, nothing lost.
        restored = {m.id for _, m in QueueWAL.replay(path)}
        assert restored == expected

    def test_crash_after_swap_replay_sees_live_set(self, tmp_path):
        path = str(tmp_path / "q.wal")
        wal, live, expected = self._seed(path)
        assert wal.begin_compact()
        n = wal.write_compact_tmp(live)
        wal.finish_compact(n)              # swap + dir fsync
        # CRASH immediately after compaction: the compacted file (and
        # its directory entry — _fsync_dir) must replay to the live
        # set, in the SAME record format as live appends.
        restored = {m.id for _, m in QueueWAL.replay(path)}
        assert restored == expected
        # And the compacted journal keeps accepting appends.
        extra = mk("after-compact")
        wal.append("push", "normal", extra.id, extra)
        wal.close()
        restored2 = {m.id for _, m in QueueWAL.replay(path)}
        assert restored2 == expected | {"after-compact"}

    def test_truncated_compacted_journal_drops_only_torn_tail(
            self, tmp_path):
        path = str(tmp_path / "q.wal")
        wal, live, expected = self._seed(path)
        assert wal.begin_compact()
        n = wal.write_compact_tmp(live)
        wal.finish_compact(n)
        wal.close()
        import os
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(size - 7)           # tear the last record
        restored = {m.id for _, m in QueueWAL.replay(path)}
        assert len(restored) == len(expected) - 1
        assert restored < expected
