"""Queue durability (write-ahead log) — capability the reference lacks:
its queues are memory-only and every pending message dies on restart
(SURVEY §5; its README claims Redis queueing it never implements)."""

import json
import threading

import pytest

from llmq_tpu.core.config import default_config
from llmq_tpu.core.types import Message, Priority
from llmq_tpu.queueing.queue_manager import QueueManager
from llmq_tpu.queueing.wal import QueueWAL


def mk(mid, prio=Priority.NORMAL, content="x"):
    return Message(id=mid, content=content, user_id="u", priority=prio)


class TestQueueWAL:
    def test_pending_survive_restart_in_order(self, tmp_path):
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)
        for i, p in enumerate([Priority.LOW, Priority.REALTIME,
                               Priority.NORMAL, Priority.REALTIME]):
            qm.push_message(mk(f"m{i}", p))
        qm.stop()

        qm2 = QueueManager("m", enable_metrics=False, wal_path=wal)
        assert qm2.total_pending() == 4
        # Priority + FIFO order preserved across restart.
        drained = qm2.drain_in_priority_order(10)
        assert [m.id for m in drained] == ["m1", "m3", "m2", "m0"]
        qm2.stop()

    def test_completed_not_restored_inflight_redelivered(self, tmp_path):
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)
        for i in range(4):
            qm.push_message(mk(f"m{i}"))
        a = qm.pop_message("normal")
        b = qm.pop_message("normal")
        qm.complete_message(a, 0.1)        # finished → gone
        # b popped but never completed → crash → must redeliver
        qm.stop()

        qm2 = QueueManager("m", enable_metrics=False, wal_path=wal)
        restored = {m.id for m in qm2.drain_in_priority_order(10)}
        assert a.id not in restored
        assert restored == {"m1", "m2", "m3"}
        qm2.stop()

    def test_requeue_and_remove_ops(self, tmp_path):
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)
        qm.push_message(mk("a"))
        qm.push_message(mk("b"))
        m = qm.pop_message("normal")
        qm.requeue_message(m)              # back to pending
        qm.remove_message("b")             # admin-removed → gone
        qm.stop()

        qm2 = QueueManager("m", enable_metrics=False, wal_path=wal)
        restored = [m.id for m in qm2.drain_in_priority_order(10)]
        assert restored == ["a"]
        qm2.stop()

    def test_corrupt_trailing_line_skipped(self, tmp_path):
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)
        qm.push_message(mk("good"))
        qm.stop()
        with open(wal, "a") as f:
            f.write('{"op": "push", "q": "normal", "id": "torn", "ms')
        qm2 = QueueManager("m", enable_metrics=False, wal_path=wal)
        assert [m.id for m in qm2.drain_in_priority_order(10)] == ["good"]
        qm2.stop()

    def test_restart_compacts_journal(self, tmp_path):
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)
        for i in range(50):
            qm.push_message(mk(f"m{i}"))
        for m in qm.drain_in_priority_order(49):
            qm.complete_message(m, 0.0)
        qm.stop()
        lines_before = sum(1 for _ in open(wal))
        assert lines_before >= 148          # 50 push + 49 pop + 49 done
        qm2 = QueueManager("m", enable_metrics=False, wal_path=wal)
        lines_after = sum(1 for _ in open(wal))
        assert lines_after == 1             # only the live message
        rec = json.loads(open(wal).readline())
        assert rec["op"] == "push" and rec["id"] == "m49"
        qm2.stop()

    def test_message_fields_roundtrip(self, tmp_path):
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)
        msg = mk("rich", Priority.HIGH, content="hello wörld")
        msg.conversation_id = "c9"
        msg.metadata["k"] = "v"
        qm.push_message(msg)
        qm.stop()
        qm2 = QueueManager("m", enable_metrics=False, wal_path=wal)
        got = qm2.pop_message("high")
        assert got.content == "hello wörld"
        assert got.conversation_id == "c9"
        assert got.metadata["k"] == "v"
        qm2.stop()

    def test_concurrent_appends_safe(self, tmp_path):
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)

        def push_many(base):
            for i in range(50):
                qm.push_message(mk(f"{base}-{i}"))

        ts = [threading.Thread(target=push_many, args=(b,))
              for b in ("a", "b", "c")]
        [t.start() for t in ts]
        [t.join() for t in ts]
        qm.stop()
        qm2 = QueueManager("m", enable_metrics=False, wal_path=wal)
        assert qm2.total_pending() == 150
        qm2.stop()

    def test_factory_wal_dir_wiring(self, tmp_path):
        from llmq_tpu.queueing.factory import QueueFactory, QueueType
        cfg = default_config()
        cfg.queue.enable_metrics = False
        cfg.queue.wal_dir = str(tmp_path)
        fac = QueueFactory(cfg)
        man = fac.create_queue_manager("std", QueueType.STANDARD)
        man.push_message(mk("f1"))
        fac.stop_all()
        assert (tmp_path / "std.wal").exists()
        fac2 = QueueFactory(cfg)
        man2 = fac2.create_queue_manager("std", QueueType.STANDARD)
        assert man2.total_pending() == 1
        fac2.stop_all()

    def test_monitor_compacts_running_journal(self, tmp_path):
        """Long-running process: the monitor tick rewrites the journal
        once dead records dominate (finding: compaction was restart-only
        → unbounded growth)."""
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal)
        qm.qconfig.stale_message_age = 0          # isolate compaction
        for i in range(400):
            qm.push_message(mk(f"m{i}"))
        for m in qm.drain_in_priority_order(399):
            qm.complete_message(m, 0.0)
        assert sum(1 for _ in open(wal)) >= 1100
        qm.run_monitor_once()
        assert sum(1 for _ in open(wal)) == 1      # only m399 lives
        qm.stop()

    def test_stale_expiry_not_resurrected(self, tmp_path, fake_clock):
        """Expired-stale messages must not come back on restart."""
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", enable_metrics=False, wal_path=wal,
                          clock=fake_clock)
        qm.qconfig.stale_message_age = 10.0
        qm.push_message(mk("old"))
        fake_clock.advance(100.0)
        qm.push_message(mk("fresh"))
        qm.run_monitor_once()                     # expires "old"
        qm.stop()
        qm2 = QueueManager("m", enable_metrics=False, wal_path=wal,
                           clock=fake_clock)
        assert [m.id for m in qm2.drain_in_priority_order(10)] == ["fresh"]
        qm2.stop()

    def test_restore_overflow_drops_not_crashes(self, tmp_path):
        """More live WAL records than queue capacity must not prevent
        startup — overflow drops loudly, service comes up."""
        cfg = default_config()
        cfg.queue.max_queue_size = 5
        wal = str(tmp_path / "q.wal")
        qm = QueueManager("m", config=cfg, enable_metrics=False,
                          wal_path=wal)
        for i in range(5):
            qm.push_message(mk(f"m{i}"))
        # Two popped-but-unfinished on top of a full queue → 7 live.
        a = qm.pop_message("normal")
        b = qm.pop_message("normal")
        qm.push_message(mk("m5"))
        qm.push_message(mk("m6"))
        qm.stop()
        qm2 = QueueManager("m", config=cfg, enable_metrics=False,
                           wal_path=wal)
        assert qm2.total_pending() == 5            # capacity, no crash
        qm2.stop()
