"""Warmup / AOT-compile path coverage.

The executor's parallel warmup AOT-compiles every program from abstract
shapes and serves through the stored executables (executor.py:_aot). A
signature drift between the ShapeDtypeStruct specs and the real call
sites would otherwise be swallowed by warmup()'s fallback and silently
reintroduce the multi-minute serial warmup — these tests make that
drift loud.
"""

import numpy as np

import jax

from llmq_tpu.engine.executor import JaxExecutor
from llmq_tpu.models.llama import init_params, llama3_tiny
from llmq_tpu.parallel import make_mesh


def build(mesh=None, chunk=4):
    cfg = llama3_tiny(max_seq_len=128)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return JaxExecutor(cfg, params, batch_size=4, page_size=16,
                       num_pages=33, chunk_size=chunk,
                       prefill_buckets=[16, 32], eos_id=-1, mesh=mesh)


class TestWarmup:
    def test_aot_programs_built_and_serving(self):
        ex = build()
        ex.warmup()
        # Loud failure if the AOT pass fell back: every program must be
        # present (a spec/signature drift would leave _aot empty).
        assert set(ex._aot) == {"prefill_b16", "prefill_b32",
                                "prefill_multi_b16", "prefill_multi_b32",
                                "decode", "decode_chunk",
                                "mixed_chunk"}, set(ex._aot)

        # Serving goes through the executables and matches the jit path.
        bt = np.zeros((4, ex.spec.max_pages_per_seq), np.int32)
        bt[0, :2] = [1, 2]
        first = ex.prefill([5, 6, 7], 0, bt[0], 0.0, 0)
        toks = np.full(4, first, np.int32)
        pos = np.full(4, 3, np.int32)
        out_aot = ex.decode_chunk(toks, pos, bt, np.zeros(4, np.float32),
                                  np.full(4, 4, np.int32))

        ex2 = build()   # no warmup: jit wrappers
        first2 = ex2.prefill([5, 6, 7], 0, bt[0], 0.0, 0)
        out_jit = ex2.decode_chunk(toks, pos, bt, np.zeros(4, np.float32),
                                   np.full(4, 4, np.int32))
        assert first == first2
        # Row 0 owns real pages; rows 1-3 point at reserved page 0,
        # whose (never-read-in-production) contents differ between a
        # warmed and an unwarmed executor — compare only the real row.
        assert (out_aot[0] == out_jit[0]).all()

    def test_warmup_on_mesh(self):
        """AOT specs carry the arrays' shardings — the mesh path must
        compile and serve through the executables too."""
        ex = build(mesh=make_mesh({"tp": 8}))
        ex.warmup()
        assert "decode_chunk" in ex._aot
        bt = np.zeros((4, ex.spec.max_pages_per_seq), np.int32)
        bt[0, :2] = [1, 2]
        first = ex.prefill([5, 6, 7], 0, bt[0], 0.0, 0)
        assert isinstance(first, int)

    def test_failed_aot_falls_back_loudly_logged(self):
        """If AOT breaks, warmup still completes via the execution pass
        (jit wrappers), nothing is half-installed in _aot, and the
        failure is logged at ERROR (not silent)."""
        import logging

        class _Boom:
            """Looks like a jit wrapper whose AOT lowering explodes but
            whose normal call path still works."""

            def __init__(self, inner):
                self.inner = inner

            def lower(self, *a, **k):
                raise RuntimeError("boom")

            def __call__(self, *a, **k):
                return self.inner(*a, **k)

        ex = build()
        ex._decode_chunk = _Boom(ex._decode_chunk)
        records = []

        class _Capture(logging.Handler):
            def emit(self, record):
                records.append(record)

        h = _Capture()
        logging.getLogger("llmq.executor").addHandler(h)
        try:
            ex.warmup()                 # must not raise
        finally:
            logging.getLogger("llmq.executor").removeHandler(h)
        assert ex._aot == {}            # nothing half-installed
        assert any("parallel AOT warmup failed" in r.getMessage()
                   for r in records)
        # Serving still works through the jit wrappers.
        bt = np.zeros((4, ex.spec.max_pages_per_seq), np.int32)
        out = ex.decode_chunk(np.zeros(4, np.int32), np.zeros(4, np.int32),
                              bt, np.zeros(4, np.float32),
                              np.ones(4, np.int32))
        assert out.shape == (4, 4)

    def test_export_cache_roundtrip(self, tmp_path, monkeypatch):
        """Warm restart via the jax.export disk cache: second warmup
        deserializes every program (no re-lowering) and serves outputs
        identical to the freshly-compiled path."""
        monkeypatch.setenv("LLMQ_EXPORT_CACHE_DIR", str(tmp_path))

        ex = build()
        ex.warmup()
        assert len(list(tmp_path.glob("*.jaxexp"))) == 7   # all exported

        bt = np.zeros((4, ex.spec.max_pages_per_seq), np.int32)
        bt[0, :2] = [1, 2]
        first = ex.prefill([5, 6, 7], 0, bt[0], 0.0, 0)
        toks = np.full(4, first, np.int32)
        pos = np.full(4, 3, np.int32)
        out_cold = ex.decode_chunk(toks, pos, bt, np.zeros(4, np.float32),
                                   np.full(4, 4, np.int32))

        ex2 = build()   # same geometry → cache hit for every program
        ex2.warmup()
        first2 = ex2.prefill([5, 6, 7], 0, bt[0], 0.0, 0)
        out_warm = ex2.decode_chunk(toks, pos, bt,
                                    np.zeros(4, np.float32),
                                    np.full(4, 4, np.int32))
        assert first == first2
        assert (out_cold[0] == out_warm[0]).all()

    def test_export_cache_key_tracks_code(self, tmp_path, monkeypatch):
        """Editing model/ops source must change the cache key — a stale
        artifact silently serving old code is the failure mode."""
        monkeypatch.setenv("LLMQ_EXPORT_CACHE_DIR", str(tmp_path))
        ex = build()
        k1 = ex._export_cache_key()
        import llmq_tpu.models as m
        import os
        llama_path = os.path.join(os.path.dirname(m.__file__), "llama.py")
        orig = open(llama_path).read()
        try:
            with open(llama_path, "a") as f:
                f.write("\n# cache-key probe\n")
            k2 = ex._export_cache_key()
        finally:
            with open(llama_path, "w") as f:
                f.write(orig)
        assert k1 != k2
