"""Worker tests.

Mirrors reference tests/priorityqueue_test.go:365-469 (end-to-end
processing via a capturing process function) — but with a fake clock and
synchronous batch ticks instead of sleeps — and covers the wiring the
reference leaves dangling: retry → delayed queue, exhaustion → DLQ."""

import threading


from llmq_tpu.core.types import Message, MessageStatus, Priority
from llmq_tpu.queueing.dead_letter_queue import DeadLetterQueue
from llmq_tpu.queueing.delayed_queue import DelayedQueue
from llmq_tpu.queueing.queue_manager import QueueManager
from llmq_tpu.queueing.worker import (
    ExponentialBackoff,
    FixedBackoff,
    Worker,
)


def make_worker(fake_clock, backend, process_fn, max_retries=3):
    qm = QueueManager("wtest", clock=fake_clock, backend=backend,
                      enable_metrics=False)
    qm.config.queue.retry.max_retries = max_retries
    dq = DelayedQueue(deliver=lambda q, m: qm.push_message(m, q or None),
                      clock=fake_clock)
    dlq = DeadLetterQueue(clock=fake_clock)
    w = Worker("w0", qm, process_fn, delayed_queue=dq,
               dead_letter_queue=dlq, clock=fake_clock)
    return qm, dq, dlq, w


class TestProcessing:
    def test_success_path(self, fake_clock, queue_backend):
        results = []
        qm, _, _, w = make_worker(
            fake_clock, queue_backend,
            lambda ctx, m: results.append(m.content))
        msgs = [Message(content=f"m{i}") for i in range(5)]
        for m in msgs:
            qm.push_message(m)
        n = w.process_batch()
        assert n == 5
        assert sorted(results) == sorted(f"m{i}" for i in range(5))
        assert all(m.status == MessageStatus.COMPLETED for m in msgs)
        assert qm.get_stats("normal").completed_count == 5
        assert w.stats.to_dict()["succeeded"] == 5

    def test_batch_respects_priority(self, fake_clock, queue_backend):
        order = []
        qm, _, _, w = make_worker(
            fake_clock, queue_backend, lambda ctx, m: order.append(m.content))
        qm.push_message(Message(content="low", priority=Priority.LOW))
        qm.push_message(Message(content="rt", priority=Priority.REALTIME))
        w.process_batch()
        assert order == ["rt", "low"]

    def test_max_batch_size(self, fake_clock, queue_backend):
        qm, _, _, w = make_worker(fake_clock, queue_backend, lambda ctx, m: None)
        w.wconfig.max_batch_size = 3
        for _ in range(10):
            qm.push_message(Message())
        assert w.process_batch() == 3
        assert qm.queue.size("normal") == 7


class TestRetry:
    def test_retry_goes_through_delayed_queue(self, fake_clock, queue_backend):
        # Fixes worker.go:227-229's immediate re-push.
        attempts = []

        def flaky(ctx, m):
            attempts.append(fake_clock.now())
            if len(attempts) < 2:
                raise RuntimeError("transient")

        qm, dq, dlq, w = make_worker(fake_clock, queue_backend, flaky)
        m = Message()
        qm.push_message(m)
        w.process_batch()
        assert len(attempts) == 1
        assert dq.size() == 1                      # waiting out the backoff
        assert qm.queue.size("normal") == 0        # NOT immediately re-pushed
        # Backoff is 1s (initial); nothing due yet.
        assert dq.run_due_once() == 0
        fake_clock.advance(1.01)
        assert dq.run_due_once() == 1
        w.process_batch()
        assert len(attempts) == 2
        assert m.status == MessageStatus.COMPLETED
        assert dlq.size() == 0

    def test_exhausted_retries_hit_dlq(self, fake_clock, queue_backend):
        def always_fail(ctx, m):
            raise ValueError("permanent")

        qm, dq, dlq, w = make_worker(fake_clock, queue_backend, always_fail,
                                     max_retries=2)
        m = Message(max_retries=2)
        qm.push_message(m)
        for _ in range(2):
            w.process_batch()
            fake_clock.advance(10.0)
            dq.run_due_once()
        w.process_batch()  # drains any final retry delivery
        assert m.status == MessageStatus.FAILED
        assert dlq.size() == 1
        item = dlq.items()[0]
        assert item.message.id == m.id
        assert item.source_queue == "normal"
        assert "permanent" in item.fail_reason
        assert qm.get_stats("normal").failed_count == 1

    def test_dlq_requeue_resets_and_reenters(self, fake_clock, queue_backend):
        calls = []

        def fail_then_ok(ctx, m):
            calls.append(1)
            if m.metadata.get("poison"):
                raise RuntimeError("bad")

        qm, dq, dlq, w = make_worker(fake_clock, queue_backend, fail_then_ok,
                                     max_retries=1)
        m = Message(max_retries=1, metadata={"poison": True})
        qm.push_message(m)
        w.process_batch()
        assert dlq.size() == 1
        m.metadata.pop("poison")
        back = dlq.requeue(m.id, qm)
        assert back.retry_count == 0
        w.process_batch()
        assert m.status == MessageStatus.COMPLETED


class TestTimeout:
    def test_successful_overrun_completes(self, fake_clock, queue_backend):
        """A process_fn that returns successfully after overrunning its
        deadline keeps its completed work (recorded as a timeout stat) —
        retrying would discard and re-execute finished work."""
        def slow_but_done(ctx, m):
            fake_clock.advance(m.timeout + 1.0)
            m.response = "done"

        qm, dq, dlq, w = make_worker(fake_clock, queue_backend,
                                     slow_but_done, max_retries=0)
        m = Message(timeout=5.0, max_retries=0)
        qm.push_message(m)
        w.process_batch()
        assert m.status == MessageStatus.COMPLETED
        assert m.response == "done"
        assert w.stats.to_dict()["timeouts"] == 1
        assert w.stats.to_dict()["succeeded"] == 1
        assert dlq.size() == 0

    def test_overrun_with_error_marks_timeout(self, fake_clock, queue_backend):
        def slow_crash(ctx, m):
            fake_clock.advance(m.timeout + 1.0)
            raise RuntimeError("wedged decode step")

        qm, dq, dlq, w = make_worker(fake_clock, queue_backend, slow_crash,
                                     max_retries=0)
        m = Message(timeout=5.0, max_retries=0)
        qm.push_message(m)
        w.process_batch()
        assert m.status == MessageStatus.TIMEOUT
        assert w.stats.to_dict()["timeouts"] == 1
        assert dlq.size() == 1


class TestBackoff:
    def test_exponential(self):
        # worker.go:258-294: initial · mult^(n-1), capped.
        b = ExponentialBackoff(initial=1.0, maximum=60.0, multiplier=2.0)
        assert b.next_backoff(1) == 1.0
        assert b.next_backoff(2) == 2.0
        assert b.next_backoff(3) == 4.0
        assert b.next_backoff(10) == 60.0

    def test_fixed(self):
        b = FixedBackoff(2.5)
        assert b.next_backoff(1) == 2.5
        assert b.next_backoff(99) == 2.5


class TestWatchdog:
    """Hard-deadline watchdog: wedged calls are abandoned at the grace
    multiple of the timeout, the freed semaphore slot is backed by a
    REPLACEMENT pool thread (capacity stays real), and slow-but-finishing
    calls inside the grace window keep their work."""

    def _worker(self, backend, handler, *, max_concurrent=1, grace=1.0):
        import time as _t
        qm = QueueManager("wd", backend=backend, enable_metrics=False)
        qm.config.queue.retry.max_retries = 0
        qm.config.queue.worker.max_concurrent = max_concurrent
        qm.config.queue.worker.process_interval = 0.01
        qm.config.queue.worker.hard_deadline = True
        qm.config.queue.worker.hard_deadline_grace = grace
        dlq = DeadLetterQueue()
        w = Worker("wd0", qm, handler, dead_letter_queue=dlq)
        return qm, dlq, w, _t

    def test_wedged_call_abandoned_and_capacity_restored(self, queue_backend):
        release = threading.Event()
        done_ok = threading.Event()

        def handler(ctx, m):
            if m.metadata.get("wedge"):
                release.wait(10.0)
            else:
                done_ok.set()

        qm, dlq, w, t = self._worker(queue_backend, handler)
        wedged = Message(id="wedged", timeout=0.1, max_retries=0,
                         metadata={"wedge": True})
        qm.push_message(wedged)
        w.start()
        try:
            deadline = t.time() + 5.0
            # Poll on the DLQ (the LAST observable effect of the failure
            # path) — status flips to TIMEOUT before the DLQ push lands.
            while dlq.size() == 0 and t.time() < deadline:
                t.sleep(0.02)
            assert wedged.status == MessageStatus.TIMEOUT
            assert dlq.size() == 1
            # max_concurrent=1 and the wedged call still occupies its
            # original thread: the next message must run on the
            # watchdog's replacement thread.
            qm.push_message(Message(id="after", timeout=5.0, max_retries=0))
            assert done_ok.wait(5.0), (
                "message dispatched after an abandonment never ran — "
                "pool capacity was not restored")
        finally:
            release.set()     # un-wedge; late return must be dropped
            t.sleep(0.1)
            w.stop()
        assert wedged.status == MessageStatus.TIMEOUT  # result stayed dropped

    def test_slow_call_inside_grace_window_completes(self, queue_backend):
        def slow(ctx, m):
            import time
            time.sleep(0.25)   # past 1× timeout, well inside 20× grace
            m.response = "done"

        qm, dlq, w, t = self._worker(queue_backend, slow, grace=20.0)
        m = Message(timeout=0.1, max_retries=0)
        qm.push_message(m)
        w.start()
        try:
            deadline = t.time() + 5.0
            while not m.status == MessageStatus.COMPLETED and t.time() < deadline:
                t.sleep(0.02)
        finally:
            w.stop()
        # Slow ≠ wedged: the work finished and must be kept (the module
        # invariant), recorded as a timeout overrun, never re-executed.
        assert m.status == MessageStatus.COMPLETED
        assert m.response == "done"
        assert w.stats.to_dict()["timeouts"] == 1
        assert dlq.size() == 0


class TestThreadedLoop:
    def test_real_loop_processes(self, queue_backend):
        # One real-time smoke test of the background loop (everything else
        # uses synchronous ticks + fake clock).
        qm = QueueManager("loop", backend=queue_backend, enable_metrics=False)
        qm.config.queue.worker.process_interval = 0.01
        done = threading.Event()
        w = Worker("w", qm, lambda ctx, m: done.set())
        w.wconfig.process_interval = 0.01
        qm.push_message(Message(content="x"))
        w.start()
        try:
            assert done.wait(timeout=5.0)
        finally:
            w.stop()
        assert not w.running
